"""Per-request cost attribution + tick-anomaly analyzer (ISSUE 13).

The load-bearing gate is CONSERVATION: on a seeded mixed
prefill+decode workload with spills/restores, greedy AND sampled, the
summed per-request receipts must equal the PerfAccountant's cumulative
tick totals EXACTLY (closed form, not banded) — integer equality, not
an approx comparison. Everything else (receipts in the finish event
and usage.cost, tenant rollups and their Prometheus counters, the
anomaly detector's classification and auto-capture) layers on that.

Every engine gets a UNIQUE Prometheus model tag so samples from other
tests sharing the process registry can never leak in.
"""

import uuid

import numpy as np
import jax.numpy as jnp
import pytest

from ray_tpu.llm._internal.anomaly import (AnomalyConfig,
                                           TickAnomalyDetector)
from ray_tpu.llm._internal.attribution import (CONSERVED_FIELDS,
                                               ReceiptLedger,
                                               _largest_remainder_split)
from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)
from ray_tpu.models import llama


def make_engine(**over):
    cfg = llama.config("debug", dtype=jnp.float32)
    kw = dict(model=cfg, max_batch_size=3, page_size=8, num_pages=64,
              prefill_buckets=(16, 32, 64), max_prefill_tokens=16,
              seed=11,
              metrics_model_id=f"at{uuid.uuid4().hex[:10]}")
    kw.update(over)
    return InferenceEngine(EngineConfig(**kw))


def _drive_mixed(eng, sampled: bool, n_req: int = 10,
                 preempt_at: int = 12):
    """Seeded bursty mixed prefill+decode workload; preempts one
    running request mid-flight so spill/restore d2h/h2d traffic is
    part of the conservation sum. Returns the requests."""
    rng = np.random.default_rng(7)
    reqs = [Request(
        f"c{i}", rng.integers(2, 250, 12 + 4 * (i % 3)).tolist(),
        SamplingParams(
            max_tokens=16 + 8 * (i % 2),
            temperature=(0.8 if sampled and i % 2 else 0.0),
            top_k=(20 if sampled and i % 2 else 0)),
        tenant=("acme" if i % 3 == 0 else ""))
        for i in range(n_req)]
    pending = list(reqs)
    steps = 0
    preempted = False
    while eng.has_work() or pending:
        if pending and steps % 4 == 0:
            for r in pending[:3]:
                eng.add_request(r)
            pending = pending[3:]
        eng.step()
        steps += 1
        if steps >= preempt_at and not preempted:
            # spill whichever request currently decodes; keep trying
            # each tick (a victim can finish inside the drain fold
            # preempt() runs first, making that attempt a no-op)
            for s in eng.slots:
                if s.request is not None and s.ready:
                    preempted = eng.preempt(s.request.request_id,
                                            reason="manual")
                    break
    assert all(r.finished for r in reqs)
    return reqs


# --------------------------------------------------------- conservation

@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
def test_receipt_conservation_exact(sampled):
    """THE gate: summed receipts == accountant totals, integer-exact,
    on a mixed prefill+decode+spill workload (acceptance criterion)."""
    eng = make_engine(enable_kv_offload=True)
    _drive_mixed(eng, sampled=sampled)
    assert eng.host_tier.spills_total >= 1      # the spill really ran
    assert eng.host_tier.restores_total >= 1
    pt = eng.perf.totals()
    at = eng.attrib.totals()
    for key, _ in CONSERVED_FIELDS:
        assert pt[key] == at[key], (
            f"conservation failed for {key}: "
            f"perf={pt[key]} receipts={at[key]}")
    # offload traffic was part of the sum, not vacuously zero
    assert at["bytes_d2h"] > 0 and at["bytes_h2d"] > 0
    # every request ended with a CLOSED receipt
    summ = eng.attrib.summary()
    assert summ["live"] == 0
    assert summ["requests_total"] == 10
    # time shares exist and sum to (at most) the engine's busy time:
    # every charged tick contributed its wall once
    total_wall = sum(r["wall_ms"] for r in summ["top"])
    assert total_wall > 0


def test_receipt_time_and_queue_shares():
    """Wall-time shares over all receipts re-sum to the committed
    ticks' walls; queue wait lands on the receipt at admission."""
    eng = make_engine()
    _drive_mixed(eng, sampled=False, preempt_at=10**9)
    ledger = eng.attrib
    rows = [ledger.receipt(f"c{i}") for i in range(10)]
    assert all(r is not None and r.finished for r in rows)
    wall_sum = sum(r.wall_ms for r in rows)
    # sum of committed PerfSample walls == sum of receipt shares
    # (float pro-rata split; tolerance for accumulation only)
    sample_wall = sum(t.wall_ms for t in eng.perf.window())
    assert wall_sum == pytest.approx(sample_wall, rel=1e-6)
    assert all(r.queue_ms >= 0.0 for r in rows)
    assert all(r.kv_page_ticks > 0 for r in rows)
    assert all(r.ticks > 0 for r in rows)


def test_largest_remainder_split_exact():
    """The weight-byte splitter: shares always re-sum to the total,
    are proportional, and degrade to equal split on zero weights."""
    rng = np.random.default_rng(3)
    for _ in range(200):
        n = int(rng.integers(1, 9))
        total = int(rng.integers(0, 10**12))
        weights = [int(w) for w in rng.integers(0, 10**6, n)]
        shares = _largest_remainder_split(total, weights)
        assert sum(shares) == total
        assert all(s >= 0 for s in shares)
        wsum = sum(weights)
        if wsum:
            for s, w in zip(shares, weights):
                assert abs(s - total * w / wsum) <= 1
    assert _largest_remainder_split(10, [0, 0, 0]) == [4, 3, 3]
    assert _largest_remainder_split(7, []) == []


def test_finish_tick_late_charges_fold_into_done_receipt():
    """A request's FINAL tick is charged before its finish lands but
    the ledger commits at step end — the late charges must fold into
    the finished receipt, never a zombie live one (conservation
    depends on it)."""
    eng = make_engine()
    rng = np.random.default_rng(5)
    eng.add_request(Request("solo", rng.integers(2, 250, 12).tolist(),
                            SamplingParams(max_tokens=6)))
    while eng.has_work():
        eng.step()
    assert eng.attrib.summary()["live"] == 0
    rec = eng.attrib.receipt("solo")
    assert rec is not None and rec.finished
    assert rec.decode_tokens == 6
    assert rec.prefill_tokens == 12
    pt = eng.perf.totals()
    assert rec.flops == pt["flops_gemm"] + pt["flops_attn"]


# --------------------------------------------- surfaces: events + usage

def test_finish_event_and_stats_carry_receipt():
    """The retirement flight-recorder event carries the cost brief;
    stats()["attribution"] ranks receipts and rolls up tenants."""
    eng = make_engine()
    _drive_mixed(eng, sampled=False, preempt_at=10**9)
    retirements = [e for e in eng.telemetry.recorder.events()
                   if e["event"] == "retirement"]
    assert retirements and all("cost" in e for e in retirements)
    c = retirements[-1]["cost"]
    for key in ("flops", "hbm_bytes", "kv_page_ticks", "wall_ms",
                "queue_ms", "decode_tokens", "prefill_tokens"):
        assert key in c
    s = eng.stats()["attribution"]
    assert s["enabled"] and s["requests_total"] == 10
    assert s["top"] and s["top"][0]["flops"] >= s["top"][-1]["flops"]
    assert set(s["tenants"]) == {"default", "acme"}
    assert s["tenants"]["acme"]["requests"] == 4
    assert s["tenants"]["default"]["requests"] == 6
    # the same doc serves GET /debug/attribution
    assert eng.attribution_summary(top_k=2)["top"] == s["top"][:2]


def test_attribution_disabled_is_inert():
    eng = make_engine(enable_attribution=False,
                      enable_anomaly_detection=False)
    _drive_mixed(eng, sampled=False, preempt_at=10**9)
    assert eng.attrib is None and eng.anomaly is None
    assert eng.stats()["attribution"] == {"enabled": False}
    assert eng.stats()["anomaly"] == {"enabled": False}


def test_attribution_requires_perf_accounting():
    eng = make_engine(enable_perf_accounting=False)
    assert eng.attrib is None and eng.anomaly is None


def test_usage_cost_block_via_server():
    """The OpenAI response's usage.cost extension (server layer)."""
    import asyncio

    from ray_tpu.llm._internal.server import LLMServerImpl

    async def main():
        server = LLMServerImpl({
            "model_id": f"uc{uuid.uuid4().hex[:8]}",
            "engine_kwargs": {"max_batch_size": 2, "page_size": 8,
                              "num_pages": 64}})
        out = await server.completions(
            {"prompt": "hello cost", "max_tokens": 6,
             "user": "tenant-x"})
        return out, server

    out, server = asyncio.new_event_loop().run_until_complete(main())
    cost = out["usage"]["cost"]
    assert cost["flops"] > 0 and cost["hbm_bytes"] > 0
    assert cost["decode_tokens"] == out["usage"]["completion_tokens"]
    # the tenant rode admission -> Request -> receipt
    tenants = server.engine.attrib.tenants()
    assert "tenant-x" in tenants


# ------------------------------------------------- anomaly: unit tests

class _GcStub:
    def __init__(self):
        self.total = 0.0
        self.collections = 0

    def snapshot(self):
        return self.total


def _warm_detector(cfg=None, n=32, wall=2.0):
    det = TickAnomalyDetector(cfg or AnomalyConfig(
        warmup_ticks=16, z_threshold=6.0, min_wall_ms=0.1))
    det._gc = _GcStub()
    det._gc_prev = 0.0

    class S:        # a PerfSample-shaped stub
        flops = 2e9
        hbm_bytes = 1e9
        bytes_h2d = 0.0
        bytes_d2h = 0.0
        kind = "decode"
        dispatches = 1
        decode_tokens = 3
        prefill_tokens = 0

    for _ in range(n):
        ev = det.observe(S(), wall, 0.2, 0.1, compiles=5,
                         peak_flops=1e12, peak_bytes=1e12)
        assert ev is None, ev
    return det, S


def test_anomaly_detector_silent_on_steady_ticks():
    det, _ = _warm_detector(n=64)
    assert det.stats()["anomalies_total"] == 0
    assert det.stats()["warmed"]
    assert det.rate() == 0.0


def test_anomaly_classification_priority():
    """Each evidence channel classifies; priority order holds."""
    det, S = _warm_detector()
    # 1) compile delta wins
    ev = det.observe(S(), 40.0, 0.2, 0.1, compiles=6,
                     peak_flops=1e12, peak_bytes=1e12)
    assert ev is not None and ev["kind"] == "recompile"
    assert ev["compile_delta"] == 1
    # 2) h2d bytes
    s = S()
    s.bytes_h2d = 4096.0
    ev = det.observe(s, 40.0, 0.2, 0.1, compiles=6,
                     peak_flops=1e12, peak_bytes=1e12)
    assert ev is not None and ev["kind"] == "h2d_transfer"
    assert ev["composition"]["bytes_h2d"] == 4096
    # 3) gc pause overlapping the tick
    det._gc.total += 0.030
    ev = det.observe(S(), 40.0, 0.2, 0.1, compiles=6,
                     peak_flops=1e12, peak_bytes=1e12)
    assert ev is not None and ev["kind"] == "gc_pause"
    assert ev["gc_pause_ms"] == pytest.approx(30.0, abs=0.5)
    # 4) host-fold stall (host share far above its baseline)
    ev = det.observe(S(), 40.0, 36.0, 0.1, compiles=6,
                     peak_flops=1e12, peak_bytes=1e12)
    assert ev is not None and ev["kind"] == "host_fold_stall"
    # 5) device straggler
    ev = det.observe(S(), 40.0, 0.2, 30.0, compiles=6,
                     peak_flops=1e12, peak_bytes=1e12)
    assert ev is not None and ev["kind"] == "device_straggler"
    # 6) no fingerprint
    ev = det.observe(S(), 40.0, 0.2, 0.1, compiles=6,
                     peak_flops=1e12, peak_bytes=1e12)
    assert ev is not None and ev["kind"] == "unknown"
    st = det.stats()
    assert st["anomalies_total"] == 6
    assert set(st["by_kind"]) == {
        "recompile", "h2d_transfer", "gc_pause", "host_fold_stall",
        "device_straggler", "unknown"}
    assert st["rate"] > 0


def test_anomaly_capture_rate_limits():
    """arm_profile/dump resolve True once per interval, not per
    anomaly — an anomaly storm must not storm the spool."""
    det, S = _warm_detector(AnomalyConfig(
        warmup_ticks=16, z_threshold=6.0, min_wall_ms=0.1,
        profile_min_interval_s=3600.0, dump_min_interval_s=3600.0))
    ev1 = det.observe(S(), 40.0, 0.2, 0.1, compiles=5,
                      peak_flops=1e12, peak_bytes=1e12)
    ev2 = det.observe(S(), 40.0, 0.2, 0.1, compiles=5,
                      peak_flops=1e12, peak_bytes=1e12)
    assert ev1["arm_profile"] and ev1["dump"]
    assert not ev2["arm_profile"] and not ev2["dump"]


def test_anomaly_unwarmed_never_triggers():
    det = TickAnomalyDetector(AnomalyConfig(warmup_ticks=1000))
    det._gc = _GcStub()
    det._gc_prev = 0.0

    class S:
        flops, hbm_bytes, bytes_h2d, bytes_d2h = 1e9, 1e9, 0.0, 0.0
        kind, dispatches, decode_tokens, prefill_tokens = "d", 1, 1, 0

    for i in range(100):
        wall = 1.0 if i % 10 else 500.0          # wild outliers
        assert det.observe(S(), wall, 0.1, 0.1, compiles=i,
                           peak_flops=1e12, peak_bytes=1e12) is None


# ------------------------------------------------ anomaly: engine e2e

def _steady_engine(**over):
    """Warmed engine in steady decode with a FAST anomaly warmup.
    Batch 4 with 3 warm requests: one slot stays free, so the test's
    injected long prompt admits (and recompiles) immediately."""
    eng = make_engine(
        max_batch_size=4, num_pages=128,
        anomaly={"warmup_ticks": 16, "z_threshold": 6.0,
                 "min_wall_ms": 0.0,
                 "profile_min_interval_s": 0.0,
                 "dump_min_interval_s": 0.0},
        **over)
    rng = np.random.default_rng(5)
    for i in range(3):
        eng.add_request(Request(
            f"s{i}", rng.integers(2, 250, 12).tolist(),
            SamplingParams(max_tokens=200)))
    while eng.waiting or any(s.request is not None and not s.ready
                             for s in eng.slots):
        eng.step()
    for _ in range(40):          # past the 16-tick warmup, baseline set
        eng.step()
    return eng


def test_forced_recompile_produces_classified_capture():
    """Acceptance criterion: an injected stall (forced recompile — a
    cold prefill bucket mid-steady-state) produces a classified
    tick_anomaly event, an auto-armed profile capture, and a black-box
    bundle in the spool."""
    eng = _steady_engine()
    assert eng.anomaly.stats()["warmed"]
    base_anoms = eng.anomaly.anomalies_total
    # force a recompile: a prompt far past every warmed bucket
    rng = np.random.default_rng(9)
    eng.add_request(Request("long", rng.integers(2, 250, 60).tolist(),
                            SamplingParams(max_tokens=4)))
    comp0 = eng.compiles
    for _ in range(30):
        eng.step()
        if eng.anomaly.anomalies_total > base_anoms:
            break
    assert eng.compiles > comp0           # the recompile really ran
    assert eng.anomaly.anomalies_total > base_anoms
    events = eng.telemetry.recorder.events()
    anoms = [e for e in events if e["event"] == "tick_anomaly"]
    assert anoms, "no tick_anomaly flight event"
    ev = anoms[0]
    assert ev["anomaly_kind"] == "recompile"
    assert ev["compile_delta"] >= 1
    assert ev["wall_ms"] > ev["predicted_ms"]
    assert "composition" in ev and ev["composition"]["dispatches"] >= 1
    # auto-armed profile capture (trigger recorded)
    armed = [e for e in events if e["event"] == "profile_armed"
             and e.get("trigger") == "tick_anomaly"]
    assert armed, "profile capture was not auto-armed"
    # black-box bundle dropped and fetchable from the spool
    bundles = eng.blackbox.list()
    causes = {b["cause"] for b in bundles}
    assert "tick_anomaly" in causes
    bid = next(b["id"] for b in bundles
               if b["cause"] == "tick_anomaly")
    bundle = eng.blackbox.read(bid)
    assert bundle["anomaly_event"]["kind"] == "recompile"
    # the triggering event must not displace the detector's stats
    assert bundle["anomaly"]["anomalies_total"] >= 1
    assert bundle["attribution"] is not None
    # anomaly state rides stats() and the fleet snapshot brief
    assert eng.stats()["anomaly"]["anomalies_total"] >= 1
    assert eng.stats()["anomaly"]["by_kind"].get("recompile", 0) >= 1


def test_anomaly_profile_arm_does_not_wedge_manual_arming():
    """After an auto-armed capture completes, POST /debug/profile
    (profile_next_ticks) still works — and an auto-arm while a manual
    capture is pending is a silent no-op, not a crash."""
    eng = _steady_engine()
    eng.profile_next_ticks(2)
    assert eng._arm_profile_locked(2) is None      # already armed
    for _ in range(3):
        eng.step()
    assert eng._profile is None                    # capture completed
    assert eng.profile_next_ticks(1)               # manual re-arm ok
    for _ in range(2):
        eng.step()


# --------------------------------------------------- ledger edge cases

def test_ledger_finish_before_first_commit():
    """An imported session (restarts >= 1, so no queue-note receipt)
    finishing inside its FIRST charged tick: the receipt must be
    issued at finish, the tick's pending charges must fold into it at
    commit, and NO zombie live receipt may leak."""
    ledger = ReceiptLedger()

    class R:
        request_id = "imported"
        tenant = "t1"
        finish_reason = "stop"

    class S:
        bytes_weights = 100.0
        wall_ms = 1.0

    r = R()
    ledger.charge(r, {"flops_gemm": 40.0}, decode_tokens=2)
    got = ledger.finish(r)                   # before any commit
    assert got is not None and got.finished
    ledger.commit(S())                       # late charges fold in
    assert ledger.summary()["live"] == 0     # no zombie
    rec = ledger.receipt("imported")
    assert rec is got
    assert rec.flops_gemm == 40 and rec.decode_tokens == 2
    assert rec.bytes_weights == 100
    t = ledger.totals()
    assert t["flops_gemm"] == 40 and t["bytes_weights"] == 100
    assert ledger.tenants()["t1"]["requests"] == 1


def test_ledger_migrated_close_not_counted_as_request():
    """An export-side 'migrated' close folds its costs into the
    tenant rollup but NOT into `requests` — the request finishes for
    real on the importing engine, and fleet-summed demand curves must
    count it once."""
    ledger = ReceiptLedger()

    class R:
        request_id = "m1"
        tenant = ""
        finish_reason = "migrated"

    class S:
        bytes_weights = 10.0
        wall_ms = 1.0

    r = R()
    ledger.charge(r, {"flops_gemm": 5.0}, prefill_tokens=1)
    ledger.commit(S())
    ledger.finish(r)
    t = ledger.tenants()["default"]
    assert t["requests"] == 0 and t["migrated"] == 1
    assert t["flops"] == 5                   # the cost still rolls up


def test_ledger_done_ring_eviction_keeps_totals():
    """Receipts displaced from the finished ring still count into
    totals() — conservation never decays with traffic volume."""
    ledger = ReceiptLedger(done_ring=4)

    class R:
        def __init__(self, rid):
            self.request_id = rid
            self.tenant = ""
            self.finish_reason = "stop"

    class S:
        bytes_weights = 100.0
        wall_ms = 1.0

    for i in range(10):
        r = R(f"r{i}")
        ledger.charge(r, {"flops_gemm": 50.0}, decode_tokens=1)
        ledger.commit(S())
        ledger.finish(r)
    t = ledger.totals()
    assert t["flops_gemm"] == 500
    assert t["bytes_weights"] == 1000
    assert t["decode_tokens"] == 10
    assert ledger.summary()["finished_retained"] == 4
    # tenant rollup saw all ten
    assert ledger.tenants()["default"]["requests"] == 10
