"""FT data plane: spilling, chunked transfer, lineage reconstruction.

Reference parity for test strategy: python/ray/tests test_object_spilling /
test_reconstruction-style suites, on the in-process multi-daemon cluster.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import object_store as ostore_mod


@pytest.fixture()
def tiny_arena_session(monkeypatch):
    # Arena must be created small BEFORE the session's first daemon starts.
    monkeypatch.setattr(ostore_mod, "ARENA_DEFAULT_BYTES", 8 << 20)
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _daemon_stats():
    from ray_tpu._private.worker import current_runtime
    import ray_tpu._private.state as state
    rt = current_runtime()
    client = state.current_client()
    return client.daemon_rpc(rt.head_daemon.address, "node_stats")


def test_spill_under_arena_pressure(tiny_arena_session):
    # 12 x 1.5 MB through an 8 MB arena: older objects must spill to disk
    # and every ref must still materialize correctly.
    arrays = [np.full((1500 * 1024 // 8,), i, np.int64) for i in range(12)]
    refs = [ray_tpu.put(a) for a in arrays]
    for i, ref in enumerate(refs):
        got = ray_tpu.get(ref)
        assert got.dtype == np.int64 and int(got[0]) == i and \
            got.nbytes == arrays[i].nbytes
    stats = _daemon_stats()
    assert stats["objects_spilled"] > 0
    assert stats["bytes_spilled"] > 0


def test_spilled_object_served_to_new_reader(tiny_arena_session):
    big = np.arange(400 * 1024, dtype=np.int64)      # ~3.2 MB
    ref = ray_tpu.put(big)
    # push enough data through to force the first object out
    fillers = [ray_tpu.put(np.zeros(400 * 1024, np.int64)) for _ in range(6)]

    @ray_tpu.remote
    def total(x):
        return int(x.sum())

    assert ray_tpu.get(total.remote(ref)) == int(big.sum())
    del fillers


@pytest.fixture()
def cluster():
    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()


def test_chunked_fetch_large_object(cluster, monkeypatch):
    import ray_tpu._private.state as state
    from ray_tpu._private.config import get_config

    monkeypatch.setattr(get_config(), "fetch_chunk_bytes", 1 << 20)
    client = state.current_client()
    # force the remote-fetch path even on one machine
    monkeypatch.setattr(client, "_shm_is_local", lambda loc: False)

    big = np.arange(5 * (1 << 20) // 8, dtype=np.int64)   # 5 MB -> 5 chunks
    ref = ray_tpu.put(big)
    client.memory_store.get_entry(ref.id).value = None
    client.memory_store.get_entry(ref.id).has_value = False
    got = ray_tpu.get(ref)
    assert np.array_equal(got, big)


def test_lineage_reconstruction_after_node_death(cluster):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    node_b = ray_tpu.add_fake_node(num_cpus=2)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_b, soft=False))
    def produce():
        return np.arange(200 * 1024, dtype=np.int64)     # > inline limit

    ref = produce.remote()
    first = ray_tpu.get(ref)
    assert int(first[-1]) == 200 * 1024 - 1

    # Drop the cached value so the next get re-reads the (dead) location.
    import ray_tpu._private.state as state
    client = state.current_client()
    entry = client.memory_store.get_entry(ref.id)
    entry.value = None
    entry.has_value = False
    entry.shm_keepalive = None

    assert ray_tpu.remove_node(node_b)
    time.sleep(0.3)
    again = ray_tpu.get(ref)                  # re-executed on surviving node
    assert np.array_equal(again, first)


def test_lineage_chain_reconstruction(cluster):
    node_b = ray_tpu.add_fake_node(num_cpus=2)
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    strat = NodeAffinitySchedulingStrategy(node_b, soft=False)

    @ray_tpu.remote(scheduling_strategy=strat)
    def base():
        return np.ones(64 * 1024, np.int64)              # > inline limit

    @ray_tpu.remote(scheduling_strategy=strat)
    def double(x):
        return x * 2

    a = base.remote()
    b = double.remote(a)
    assert int(ray_tpu.get(b)[0]) == 2

    import ray_tpu._private.state as state
    client = state.current_client()
    for ref in (a, b):
        e = client.memory_store.get_entry(ref.id)
        e.value = None
        e.has_value = False
        e.shm_keepalive = None

    assert ray_tpu.remove_node(node_b)
    time.sleep(0.3)
    # b's re-execution must recursively reconstruct a on the live node
    assert int(ray_tpu.get(b)[0]) == 2


def test_put_object_lost_is_not_reconstructable(cluster):
    # put() has no lineage: losing the only copy must raise ObjectLostError.
    import ray_tpu._private.state as state
    client = state.current_client()
    ref = ray_tpu.put(np.zeros(64 * 1024, np.int64))
    entry = client.memory_store.get_entry(ref.id)
    loc = entry.location
    assert loc is not None
    entry.value = None
    entry.has_value = False
    client.daemon_rpc(loc.node_addr, "free_object", object_id=ref.id)
    with pytest.raises(ray_tpu.exceptions.ObjectLostError):
        ray_tpu.get(ref)


@pytest.fixture()
def remote_spill_session(monkeypatch):
    """Tiny arena + mock:// remote spill backend (VERDICT r4 missing
    #3: reference external_storage.py fs/S3/mock backends — ours rides
    the train/storage pyarrow-fs layer, so gs:// works the same way)."""
    monkeypatch.setattr(ostore_mod, "ARENA_DEFAULT_BYTES", 8 << 20)
    monkeypatch.setenv("RAY_TPU_SPILL_STORAGE", "mock://spill-bucket")
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_spill_to_remote_backend(remote_spill_session):
    """Pressure spills land in the mock:// filesystem (not local disk),
    restore transparently on read, and delete on free."""
    from ray_tpu.train.storage import get_fs_and_path
    arrays = [np.full((1500 * 1024 // 8,), i, np.int64) for i in range(12)]
    refs = [ray_tpu.put(a) for a in arrays]
    for i, ref in enumerate(refs):
        got = ray_tpu.get(ref)
        assert int(got[0]) == i and got.nbytes == arrays[i].nbytes
    stats = _daemon_stats()
    assert stats["objects_spilled"] > 0
    # spilled bytes live in the remote fs, visible via the same layer
    fs, path = get_fs_and_path("mock://spill-bucket")
    import pyarrow.fs as pafs
    infos = fs.get_file_info(pafs.FileSelector(path, recursive=True))
    assert any(f.size and f.size > 1 << 20 for f in infos), \
        "no spilled object found in the remote backend"
    # freeing the refs deletes the remote spill files
    n_before = len(infos)
    del refs
    import gc
    gc.collect()
    deadline = time.time() + 20
    while time.time() < deadline:
        infos = fs.get_file_info(pafs.FileSelector(path, recursive=True))
        if len(infos) < n_before:
            break
        time.sleep(0.25)
    assert len(infos) < n_before, "remote spill files not reclaimed"
