"""Local-mode streaming generator parity."""

import ray_tpu


def test_local_mode_streaming(ray_local):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    assert [ray_tpu.get(r) for r in gen.remote(3)] == [0, 1, 2]
