"""jaxlint analyzer property tests (ISSUE 3, tools/jaxlint).

Per-rule synthetic modules (positive AND negative cases, decorator and
functional `jax.jit` forms, `shard_map` wrapping, cross-module traced
reachability) so rule regressions are caught without running against
ray_tpu/ — plus the tier-1 repo gates: the shipped baseline is small,
justified, and `python -m tools.jaxlint ray_tpu` is clean against it
while a seeded violation still fails.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.jaxlint import analyze_paths, load_baseline
from tools.jaxlint.rules import ALL_RULES

REPO = Path(__file__).resolve().parent.parent


def _lint(tmp_path, source, name="mod.py", select=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analyze_paths([str(p)], root=str(tmp_path), select=select)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------------ JL001

@pytest.mark.parametrize("body,flagged", [
    ("np.asarray(x)", True),
    ("x.item()", True),
    ("x.tolist()", True),
    ("float(x)", True),
    ("float(3.0)", False),          # constant: trace-time no-op
    ("jnp.asarray(x)", False),      # jnp on a tracer is free
])
def test_jl001_decorator_form(tmp_path, body, flagged):
    fs = _lint(tmp_path, f"""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            y = {body}
            return y
    """, select={"JL001"})
    assert ("JL001" in _rules(fs)) is flagged


def test_jl001_functional_form_and_propagation(tmp_path):
    """jax.jit(run) marks run traced; run -> helper propagates by
    call-name so the sync inside the HELPER is flagged."""
    fs = _lint(tmp_path, """
        import jax
        import numpy as np

        def helper(y):
            return np.asarray(y)

        def entry(x):
            def run(y):
                return helper(y)
            return jax.jit(run)(x)
    """, select={"JL001"})
    assert len(fs) == 1
    assert fs[0].func == "helper"


def test_jl001_host_code_not_flagged(tmp_path):
    fs = _lint(tmp_path, """
        import numpy as np

        def host(x):
            return np.asarray(x).item()
    """, select={"JL001"})
    assert fs == []


def test_jl001_shard_map_wrapping(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        import numpy as np
        from jax.experimental.shard_map import shard_map

        def body(x):
            return np.asarray(x)

        def apply(mesh, x):
            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    """, select={"JL001"})
    assert len(fs) == 1 and fs[0].func == "body"


def test_jl001_cross_module_reachability(tmp_path):
    """The engine pattern: jax.jit(self._build()) factory whose inner
    fn calls an imported helper — the sync in the OTHER module is
    reachable and flagged."""
    (tmp_path / "ops_mod.py").write_text(textwrap.dedent("""
        def helper(x):
            return x.tolist()
    """))
    (tmp_path / "eng_mod.py").write_text(textwrap.dedent("""
        import jax
        from ops_mod import helper

        class Eng:
            def _build(self):
                def run(k_pages, x):
                    return helper(x), k_pages
                return run

            def setup(self, x):
                self.fn = jax.jit(self._build(),
                                  donate_argnums=(0,))
    """))
    fs = analyze_paths([str(tmp_path)], root=str(tmp_path),
                       select={"JL001"})
    assert len(fs) == 1
    assert fs[0].path == "ops_mod.py" and fs[0].func == "helper"


# ------------------------------------------------------------------ JL002

@pytest.mark.parametrize("jit,flagged", [
    ("jax.jit(run)", True),
    ("jax.jit(run, donate_argnums=(1, 2))", False),
    ("jax.jit(run, donate_argnums=(1,))", True),     # v_pages missed
    ("jax.jit(run, donate_argnames=('k_pages', 'v_pages'))", False),
])
def test_jl002_functional_form(tmp_path, jit, flagged):
    fs = _lint(tmp_path, f"""
        import jax

        def run(params, k_pages, v_pages, tokens):
            return tokens, k_pages, v_pages

        fn = {jit}
    """, select={"JL002"})
    assert ("JL002" in _rules(fs)) is flagged


@pytest.mark.parametrize("dec,flagged", [
    ("@jax.jit", True),
    ("@functools.partial(jax.jit, donate_argnums=(1, 2))", False),
    ("@functools.partial(jax.jit, donate_argnums=(1,))", True),
])
def test_jl002_decorator_form(tmp_path, dec, flagged):
    fs = _lint(tmp_path, f"""
        import functools
        import jax

        {dec}
        def step(params, k_pages, v_pages):
            return k_pages, v_pages
    """, select={"JL002"})
    assert ("JL002" in _rules(fs)) is flagged


def test_jl002_partial_bound_name(tmp_path):
    """jax.jit(g) where g = functools.partial(f, ...) resolves through
    the binding — same resolver behavior as traced seeding."""
    fs = _lint(tmp_path, """
        import functools
        import jax

        def run(params, k_pages, v_pages):
            return k_pages, v_pages

        def setup(params):
            g = functools.partial(run, params)
            return jax.jit(g)
    """, select={"JL002"})
    assert len(fs) == 1 and "k_pages" in fs[0].message


def test_jl002_partial_bound_name_with_shifted_donation(tmp_path):
    """partial(run, params) binds arg 0, so the jit-level donation
    indices shift down by one: donate_argnums=(0, 1) covers
    k_pages/v_pages and must NOT be flagged."""
    fs = _lint(tmp_path, """
        import functools
        import jax

        def run(params, k_pages, v_pages):
            return k_pages, v_pages

        def setup(params):
            g = functools.partial(run, params)
            return jax.jit(g, donate_argnums=(0, 1))
    """, select={"JL002"})
    assert fs == []


def test_jl002_factory_pattern(tmp_path):
    """jax.jit(build()) resolves through the factory's returned def."""
    fs = _lint(tmp_path, """
        import jax

        def build():
            def run(params, k_pages, v_pages):
                return k_pages, v_pages
            return run

        fn = jax.jit(build())
    """, select={"JL002"})
    assert len(fs) == 1 and "k_pages" in fs[0].message


@pytest.mark.parametrize("donate,flagged", [
    ("donate_argnums=(1, 2)", False),
    ("donate_argnums=(1,)", True),       # v_pages missed
], ids=["donated", "v_pages_missed"])
def test_jl002_sees_through_shard_map_body(tmp_path, donate, flagged):
    """ISSUE 17 engine pattern: the jitted tick's BODY builds a
    shard_map around a shard-local core, but donation attaches to
    the OUTER def's k_pages/v_pages params. The analyzer must judge
    that outer signature — the shard_map wrapper inside must neither
    hide a missing donation nor trip a false positive on the
    shard-local function's own pool params."""
    fs = _lint(tmp_path, f"""
        import jax
        from jax.experimental.shard_map import shard_map

        def build(mesh, specs, rep):
            def run(params, k_pages, v_pages, tokens):
                def local(p, k, v, t):
                    return t, k, v
                sm = shard_map(local, mesh,
                               in_specs=(rep, specs, specs, rep),
                               out_specs=(rep, specs, specs))
                return sm(params, k_pages, v_pages, tokens)
            return jax.jit(run, {donate})
    """, select={"JL002"})
    assert ("JL002" in _rules(fs)) is flagged


# ------------------------------------------------------------------ JL003

def test_jl003_unhashable_static_arg(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        fn = jax.jit(lambda x, cfg: x, static_argnums=(1,))

        def call(x):
            return fn(x, [1, 2])
    """, select={"JL003"})
    assert len(fs) == 1 and "unhashable" in fs[0].message


def test_jl003_python_scalar_at_traced_position(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        fn = jax.jit(lambda x, y: x + y)

        def call(x, xs):
            a = fn(x, 3)            # literal at traced position
            b = fn(x, len(xs))      # host scalar per call
            c = fn(x, x)            # device arg: fine
            return a, b, c
    """, select={"JL003"})
    assert len(fs) == 2


def test_jl003_unrelated_local_name_not_collided(tmp_path):
    """A local `fn = jax.jit(...)` in ONE function must not make every
    `fn(...)` call in the module look jitted (scope-aware lookup)."""
    fs = _lint(tmp_path, """
        import jax

        def host_path(make_formatter):
            fn = make_formatter()
            return fn(3)            # plain host call: no finding

        def jit_path(x):
            fn = jax.jit(lambda a, b: a + b)
            return fn(x, 3)         # literal at traced position
    """, select={"JL003"})
    assert len(fs) == 1 and fs[0].func == "jit_path"


def test_jl003_static_position_scalar_ok(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        fn = jax.jit(lambda x, flag: x, static_argnums=(1,))

        def call(x):
            return fn(x, True)      # static flag: the sanctioned form
    """, select={"JL003"})
    assert fs == []


# ------------------------------------------------------------------ JL004

def test_jl004_global_subscript_mutation(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        cache = {}

        @jax.jit
        def f(x):
            cache["last"] = x
            return x
    """, select={"JL004"})
    assert len(fs) == 1 and "cache" in fs[0].message


def test_jl004_host_closure_append_leak(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        def build():
            acc = []

            @jax.jit
            def g(y):
                acc.append(y)
                return y
            return g
    """, select={"JL004"})
    assert len(fs) == 1 and "acc" in fs[0].message


def test_jl004_self_attr_assignment(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        class M:
            @jax.jit
            def f(self, x):
                self.last = x
                return x
    """, select={"JL004"})
    assert len(fs) == 1 and "self.last" in fs[0].message


def test_jl004_pallas_scratch_refs_not_flagged(tmp_path):
    """Writing an ENCLOSING TRACED function's locals (Pallas refs,
    online-softmax scratch) is the kernel idiom, not a leak."""
    fs = _lint(tmp_path, """
        import jax

        @jax.jit
        def kernel(o_ref, x):
            def _finish():
                o_ref[0] = x
            _finish()
            return o_ref
    """, select={"JL004"})
    assert fs == []


# ------------------------------------------------------------------ JL005

def test_jl005_device_get_in_host_loop(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        def drain(xs):
            out = []
            for x in xs:
                out.append(jax.device_get(x))
            return out
    """, select={"JL005"})
    assert len(fs) == 1


def test_jl005_sanctioned_and_boundary_syncs_ok(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        def boundary(x):
            return jax.device_get(x)        # once, at the API edge

        def bench_loop(xs):
            for x in xs:                    # sanctioned by name
                jax.block_until_ready(x)
    """, select={"JL005"})
    assert fs == []


def test_jl005_block_until_ready_in_traced_fn(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            return jax.block_until_ready(x)
    """, select={"JL005"})
    assert len(fs) == 1


def test_jl005_bare_asarray_on_dispatch_result(tmp_path):
    """ISSUE 4: np.asarray on a jitted call's result is an
    unsanctioned sync point — direct, via a named binding, and via
    tuple-unpack (the engine's `toks, pools = fn(...)` shape)."""
    fs = _lint(tmp_path, """
        import jax
        import numpy as np

        fn = jax.jit(lambda x: (x + 1, x * 2))

        def tick_direct(x):
            return np.asarray(fn(x))

        def tick_named(x):
            toks = fn(x)
            return np.asarray(toks)

        def tick_unpacked(x):
            toks, pool = fn(x)
            return np.asarray(toks)
    """, select={"JL005"})
    assert len(fs) == 3
    assert all(f.detail == "np.asarray:dispatch-result" for f in fs)


def test_jl005_asarray_on_jit_factory_result(tmp_path):
    """The engine's memoized-factory idiom: `fn = self._ragged_fn(...)`
    yields a jitted binding, so reading its call result with
    np.asarray is a dispatch-result sync; the same method reading it
    through a helper (`self._read_tokens`) is not."""
    fs = _lint(tmp_path, """
        import jax
        import numpy as np

        class Eng:
            def _ragged_fn(self, b):
                fn = self._cache.get(b)
                if fn is None:
                    fn = jax.jit(lambda x: x * b)
                return fn

            def tick(self, x):
                out = self._ragged_fn(2)(x)
                bad = np.asarray(out)
                toks = self._ragged_fn(4)(x)
                good = self._read_tokens(toks)
                return bad, good
    """, select={"JL005"})
    assert len(fs) == 1
    assert fs[0].func == "Eng.tick"


def test_jl005_asarray_on_decorated_jit_result(tmp_path):
    """The plain @jax.jit decorator form dispatches too; a helper
    that is merely REACHABLE from traced code (not itself jitted)
    returns plain arrays from host calls and must stay clean."""
    fs = _lint(tmp_path, """
        import functools

        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return helper(x) + 1

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step2(x):
            return x * 2

        def helper(y):
            return y

        def tick(x):
            a = np.asarray(step(x))           # decorated dispatch
            b = np.asarray(step2(x))          # partial-decorated
            c = np.asarray(helper(x))         # traced-reachable only
            return a, b, c
    """, select={"JL005"})
    assert len(fs) == 2
    assert all(f.func == "tick" for f in fs)


def test_jl005_asarray_negatives(tmp_path):
    """Host arrays, non-jit call results, suppressed sanctioned
    sites, and bench/test modules stay clean."""
    fs = _lint(tmp_path, """
        import jax
        import numpy as np

        fn = jax.jit(lambda x: x + 1)

        def build(host_rows):
            return np.asarray(host_rows)          # plain host data

        def helper(x):
            return x

        def boundary(x):
            y = helper(x)
            return np.asarray(y)                  # not a dispatch

        def sanctioned_fold(x):
            toks = fn(x)
            return np.asarray(toks)  # jaxlint: disable=JL005 -- the one fold site
    """, select={"JL005"})
    assert fs == []
    # bench/profiling modules exist to block: exempt by name
    fs = _lint(tmp_path, """
        import jax
        import numpy as np

        fn = jax.jit(lambda x: x + 1)

        def timed(x):
            return np.asarray(fn(x))
    """, name="bench_mod.py", select={"JL005"})
    assert fs == []


# ------------------------------------------------------------------ JL006

def test_jl006_upload_in_host_loop(tmp_path):
    fs = _lint(tmp_path, """
        import jax.numpy as jnp

        def upload_all(xs):
            out = []
            for x in xs:
                out.append(jnp.asarray(x))
            return out
    """, select={"JL006"})
    assert len(fs) == 1


def test_jl006_loop_iterable_and_traced_ok(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        def once(xs, host):
            for row in jnp.asarray(host):   # evaluated ONCE
                xs.append(row)
            return xs

        @jax.jit
        def traced(x):
            return jnp.asarray(x)           # free on a tracer
    """, select={"JL006"})
    assert fs == []


def test_jl006_comprehension_counts_as_loop(tmp_path):
    fs = _lint(tmp_path, """
        import jax.numpy as jnp

        def per_key(batch):
            return {k: jnp.asarray(v) for k, v in batch.items()}
    """, select={"JL006"})
    assert len(fs) == 1


# ------------------------------------------------------------------ JL007

def test_jl007_wall_clock_and_host_rng_under_trace(tmp_path):
    fs = _lint(tmp_path, """
        import time

        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return x * time.time() + np.random.rand()

        def host():
            return time.time()
    """, select={"JL007"})
    assert len(fs) == 2
    assert all(f.func == "f" for f in fs)


# ------------------------------------------------------------------ JL008

def test_jl008_jit_in_loop(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        def build(n):
            out = []
            for i in range(n):
                out.append(jax.jit(lambda x: x + i))
            return out
    """, select={"JL008"})
    assert len(fs) == 1


def test_jl008_memoized_builder_ok(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        _cache = {}

        def get_fn(bucket):
            fn = _cache.get(bucket)
            if fn is None:
                fn = jax.jit(lambda x: x * bucket)
                _cache[bucket] = fn
            return fn
    """, select={"JL008"})
    assert fs == []


# ------------------------------------------------------------------ JL009

def test_jl009_instrumentation_under_trace(tmp_path):
    """metrics/tracing/telemetry calls inside a traced function run at
    trace time only (frozen into the program) — flagged; the same
    calls in host code are the intended pattern — clean."""
    fs = _lint(tmp_path, """
        import jax
        from ray_tpu.util import metrics, tracing

        ttft = metrics.Histogram("x_seconds")

        @jax.jit
        def f(x, dt):
            ttft.observe(dt)
            with tracing.span("tick"):
                pass
            return x
    """, select={"JL009"})
    assert len(fs) == 2
    assert {f.detail for f in fs} == {"ttft.observe", "tracing.span"}


def test_jl009_self_telemetry_and_recorder_forms(tmp_path):
    fs = _lint(tmp_path, """
        import jax

        class Engine:
            def _build(self):
                def run(tokens):
                    self.telemetry.on_token(tokens)
                    self.telemetry.recorder.record("tick")
                    return tokens
                return run

            def go(self, x):
                return jax.jit(self._build())(x)
    """, select={"JL009"})
    assert len(fs) == 2
    assert all(f.func.endswith("run") for f in fs)


def test_jl009_attribution_anomaly_receivers(tmp_path):
    """ISSUE 13 regression: the attribution ledger / anomaly detector
    receivers are instrumentation — a charge or observe call frozen
    under a trace would record once at trace time and never again
    (and its wall-clock reads are host work). Flagged under jit;
    clean as the engine's actual host-side pattern."""
    fs = _lint(tmp_path, """
        import jax

        @jax.jit
        def f(self, x):
            self.attrib.charge(self.req, {}, decode_tokens=1)
            self.anomaly.observe(x, 1.0, 0.0, 0.0, 0, 1.0, 1.0)
            return x
    """, select={"JL009"})
    assert {f.detail for f in fs} == {"self.attrib.charge",
                                      "self.anomaly.observe"}
    fs = _lint(tmp_path, """
        def tick(self, wall):              # host side of the boundary
            self.attrib.commit(self.sample, host_ms=wall)
            self.anomaly.observe(self.sample, wall, 0.0, 0.0,
                                 self.compiles, 1.0, 1.0)
    """, select={"JL009"})
    assert fs == []


def test_jl009_host_side_instrumentation_clean(tmp_path):
    """The engine's actual pattern — recording from host-side fold /
    admission code and bare `observe(...)` world-model calls under
    trace (dreamer) — must stay clean."""
    fs = _lint(tmp_path, """
        import jax
        from ray_tpu.util import metrics

        itl = metrics.Histogram("itl_seconds")

        def fold(engine, toks, dt):        # host side of the boundary
            itl.observe(dt)
            engine.telemetry.on_token(toks)

        @jax.jit
        def world_model(params, x):
            return observe(params, x)      # bare fn, not a handle

        def observe(params, x):
            return params * x
    """, select={"JL009"})
    assert fs == []


# ----------------------------------------------------------- suppressions

def test_inline_disable_comment(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)  # jaxlint: disable=JL001 -- test fixture
    """, select={"JL001"})
    assert fs == []


def test_function_level_disable_on_signature(tmp_path):
    fs = _lint(tmp_path, """
        import jax.numpy as jnp

        def upload_all(xs,
                       extra=None):  # jaxlint: disable=JL006 -- fixture
            return [jnp.asarray(x) for x in xs]
    """, select={"JL006"})
    assert fs == []


# ------------------------------------------------------- CLI + baseline

BAD_SOURCE = """
import jax
import numpy as np

@jax.jit
def f(x):
    return np.asarray(x)
"""


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", *args],
        cwd=str(cwd), capture_output=True, text=True)


def test_cli_seeded_violation_exits_nonzero(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(BAD_SOURCE)
    proc = _cli(str(bad), "--root", str(tmp_path))
    assert proc.returncode == 1
    assert "JL001" in proc.stdout


def test_cli_fix_baseline_roundtrip(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(BAD_SOURCE)
    base = tmp_path / "baseline.json"
    proc = _cli(str(bad), "--root", str(tmp_path), "--baseline",
                str(base), "--fix-baseline")
    assert proc.returncode == 0
    entries = json.loads(base.read_text())["entries"]
    assert len(entries) == 1
    assert entries[0]["justification"].startswith("TODO")
    # baselined -> clean exit
    proc = _cli(str(bad), "--root", str(tmp_path), "--baseline",
                str(base))
    assert proc.returncode == 0
    # fixing the file leaves a STALE entry: still exit 0, but warned
    bad.write_text("x = 1\n")
    proc = _cli(str(bad), "--root", str(tmp_path), "--baseline",
                str(base))
    assert proc.returncode == 0
    assert "stale" in proc.stderr


def test_baseline_counts_gate_added_occurrences(tmp_path):
    """Keys are line-independent, so entries carry occurrence COUNTS:
    a second identical violation in an already-baselined function is
    NEW (fails), and fixing one of N warns as partially stale."""
    def src(n):
        lines = "\n".join(f"    x{i} = np.asarray(x)" for i in range(n))
        return (f"import jax\nimport numpy as np\n\n@jax.jit\n"
                f"def f(x):\n{lines}\n    return x\n")

    mod = tmp_path / "counted.py"
    base = tmp_path / "b.json"
    mod.write_text(src(2))
    proc = _cli(str(mod), "--root", str(tmp_path), "--baseline",
                str(base), "--fix-baseline")
    assert proc.returncode == 0
    entry = json.loads(base.read_text())["entries"][0]
    assert entry["count"] == 2
    # same two occurrences -> clean
    assert _cli(str(mod), "--root", str(tmp_path), "--baseline",
                str(base)).returncode == 0
    # a THIRD identical-key violation -> new finding, lint fails
    mod.write_text(src(3))
    assert _cli(str(mod), "--root", str(tmp_path), "--baseline",
                str(base)).returncode == 1
    # one of the two fixed -> clean but flagged partially stale
    mod.write_text(src(1))
    proc = _cli(str(mod), "--root", str(tmp_path), "--baseline",
                str(base))
    assert proc.returncode == 0
    assert "occurrences fixed" in proc.stderr


def test_fix_baseline_scoped_run_preserves_out_of_scope_entries(
        tmp_path):
    """--fix-baseline on a SUBSET of the tree must not destroy
    baseline entries for files it did not analyze, and refuses
    --select outright (a rule-filtered rewrite would drop every
    unselected rule's entries)."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "mod_a.py").write_text(BAD_SOURCE)
    (tmp_path / "b" / "mod_b.py").write_text(BAD_SOURCE)
    base = tmp_path / "b.json"
    proc = _cli(str(tmp_path / "a"), str(tmp_path / "b"),
                "--root", str(tmp_path), "--baseline", str(base),
                "--fix-baseline")
    assert proc.returncode == 0
    assert len(json.loads(base.read_text())["entries"]) == 2
    # scoped rewrite over a/ only: b/'s entry survives untouched
    proc = _cli(str(tmp_path / "a"), "--root", str(tmp_path),
                "--baseline", str(base), "--fix-baseline")
    assert proc.returncode == 0
    keys = {e["key"] for e in json.loads(base.read_text())["entries"]}
    assert any("b/mod_b.py" in k for k in keys)
    assert any("a/mod_a.py" in k for k in keys)
    # --select + --fix-baseline is a usage error
    proc = _cli(str(tmp_path / "a"), "--root", str(tmp_path),
                "--baseline", str(base), "--fix-baseline",
                "--select", "JL001")
    assert proc.returncode == 2


def test_cli_unknown_rule_is_usage_error(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text("x = 1\n")
    proc = _cli(str(bad), "--select", "JL999")
    assert proc.returncode == 2


# ----------------------------------------------------- tier-1 repo gates

def test_repo_is_clean_against_shipped_baseline():
    """THE tier-1 lint gate: new findings in ray_tpu/ fail the suite."""
    proc = _cli("ray_tpu", "--baseline", "tools/jaxlint/baseline.json")
    assert proc.returncode == 0, (
        "new jaxlint findings (fix them or baseline WITH a "
        "justification):\n" + proc.stdout)


def test_shipped_baseline_is_small_and_justified():
    base = load_baseline(str(REPO / "tools/jaxlint/baseline.json"))
    assert len(base.entries) <= 15
    for key, justification in base.entries.items():
        assert justification and not justification.startswith("TODO"), (
            f"baseline entry without a real justification: {key}")
        rule = key.split(":", 1)[0]
        assert rule in ALL_RULES


def test_engine_hot_path_has_zero_baselined_findings():
    """The burndown contract: engine.py, llama_infer.py, ops/, and
    the observability modules riding the engine (telemetry.py,
    blackbox.py — ISSUE 5/7; perfmodel.py — ISSUE 11), plus the
    ISSUE 10 KV memory hierarchy (kv_offload.py host tier +
    kv_cache.py allocator), own no baseline entries — their findings
    were fixed or carry inline justified suppressions."""
    base = load_baseline(str(REPO / "tools/jaxlint/baseline.json"))
    for key in base.entries:
        path = key.split(":")[1]
        assert "llm/_internal/engine.py" not in path
        assert "llm/_internal/telemetry.py" not in path
        assert "llm/_internal/blackbox.py" not in path
        assert "llm/_internal/kv_offload.py" not in path
        assert "llm/_internal/kv_cache.py" not in path
        assert "llm/_internal/perfmodel.py" not in path
        assert "llm/_internal/attribution.py" not in path
        assert "llm/_internal/anomaly.py" not in path
        assert "models/llama_infer.py" not in path
        assert "/ops/" not in path
    # the ISSUE 10 offload/preemption module exists inside the
    # analyzed package and the gate moves with it if it ever moves
    assert (REPO / "ray_tpu/llm/_internal/kv_offload.py").exists()
    proc = _cli("ray_tpu/llm/_internal/kv_offload.py")
    assert proc.returncode == 0, (
        "jaxlint findings in kv_offload.py (zero-entry module):\n"
        + proc.stdout)
    # ISSUE 11: the perf-accounting plane is host-only arithmetic
    # riding the tick path — any jaxlint finding there is a real bug
    assert (REPO / "ray_tpu/llm/_internal/perfmodel.py").exists()
    proc = _cli("ray_tpu/llm/_internal/perfmodel.py")
    assert proc.returncode == 0, (
        "jaxlint findings in perfmodel.py (zero-entry module):\n"
        + proc.stdout)
    # ISSUE 13: the attribution/anomaly planes ride the same tick
    # path under the same contract (pure host arithmetic, no jax)
    for fname in ("attribution.py", "anomaly.py"):
        path = REPO / "ray_tpu/llm/_internal" / fname
        assert path.exists(), fname
        proc = _cli(f"ray_tpu/llm/_internal/{fname}")
        assert proc.returncode == 0, (
            f"jaxlint findings in {fname} (zero-entry module):\n"
            + proc.stdout)
    # ISSUE 16: the quantization layer (page quantizer + EQuARX-style
    # collectives) sits on the dispatch hot path — zero baseline, any
    # finding is a real bug
    for fname in ("kv_quant.py", "quantized_collectives.py"):
        path = REPO / "ray_tpu/ops" / fname
        assert path.exists(), fname
        proc = _cli(f"ray_tpu/ops/{fname}")
        assert proc.returncode == 0, (
            f"jaxlint findings in {fname} (zero-entry module):\n"
            + proc.stdout)
    # ISSUE 17: the named-mesh builder feeds every explicit-tp
    # engine's shard_map'd tick — zero baseline, any finding is a
    # real bug
    assert (REPO / "ray_tpu/ops/tp_mesh.py").exists()
    proc = _cli("ray_tpu/ops/tp_mesh.py")
    assert proc.returncode == 0, (
        "jaxlint findings in tp_mesh.py (zero-entry module):\n"
        + proc.stdout)


def test_serve_llm_fleet_has_zero_baselined_findings():
    """ISSUE 6/7/9 gate: the serve/llm fleet package (router,
    admission, autoscaler, fleet manager, deployment builder — plus
    the ISSUE 7 watchdog and trace-merge modules and the ISSUE 9
    failure plane: chaos.py and failover.py) stays at ZERO baseline
    entries — it is pure host-side control plane, so any jaxlint
    finding there is a real bug, not debt. Failure handling in
    particular must add zero device work (the chaos/dispatch-guard
    suite enforces the runtime half of that contract)."""
    base = load_baseline(str(REPO / "tools/jaxlint/baseline.json"))
    for key in base.entries:
        assert "serve/llm/" not in key.split(":")[1]
    # the ISSUE 9 modules exist and are inside the analyzed package
    # (if they ever move, this gate must move with them) — plus the
    # ISSUE 12 KV transport (wire codec + fleet shipping policy:
    # pure host-side numpy/stdlib, so any finding there is a bug),
    # the ISSUE 14 batch lane, and the ISSUE 14 simulator package
    # (pure stdlib discrete-event code: the one place a stray jax
    # import would be an architecture error, not just debt)
    for fname in ("chaos.py", "failover.py", "watchdog.py",
                  "tracemerge.py", "kv_transport.py", "batch.py",
                  "sim/core.py", "sim/replica.py", "sim/traffic.py",
                  "sim/calibration.py", "sim/capacity.py",
                  "trafficlog.py"):
        assert (REPO / "ray_tpu/serve/llm" / fname).exists(), fname
    # and the package is clean with NO baseline at all
    proc = _cli("ray_tpu/serve/llm")
    assert proc.returncode == 0, (
        "jaxlint findings in ray_tpu/serve/llm (zero-entry package):\n"
        + proc.stdout)


def test_unified_lint_runner_runs_every_analyzer():
    """ISSUE 20 satellite: `python -m tools.lint` is the one
    pre-commit gate — a single invocation runs jaxlint AND racelint
    over the same discovered file set, each against its committed
    baseline, and exits 0 only when both are clean."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "ray_tpu/serve/llm",
         "tools/tracereplay", "tools/lint"],
        cwd=str(REPO), capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"unified lint gate failed:\n{proc.stdout}\n{proc.stderr}")
    # both analyzers reported (clean or baselined) — neither was
    # silently skipped
    assert "[jaxlint]" in proc.stderr
    assert "[racelint]" in proc.stderr
    # a nonexistent path is a usage error, not a silent no-op sweep
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "no/such/dir"],
        cwd=str(REPO), capture_output=True, text=True)
    assert proc.returncode == 2
    # machine-readable mode round-trips as JSON keyed per analyzer
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--json",
         "tools/lint"],
        cwd=str(REPO), capture_output=True, text=True)
    assert proc.returncode == 0
    report = json.loads(proc.stdout)
    assert set(report) == {"jaxlint", "racelint"}
    assert report["jaxlint"]["new"] == []
