"""Streaming generators (num_returns="streaming").

Reference parity: python/ray/_raylet.pyx:295 ObjectRefGenerator +
task_manager.h:364 — generator tasks' yields are consumed incrementally
across processes, with backpressure, for both tasks and actor methods.
"""

import time

import numpy as np
import pytest

import ray_tpu


def test_task_streaming_basic(ray_start):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    out = [ray_tpu.get(ref) for ref in gen.remote(5)]
    assert out == [0, 1, 4, 9, 16]


def test_task_streaming_incremental(ray_start):
    """Items are consumable before the generator finishes."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(3):
            time.sleep(0.3)
            yield i

    t0 = time.time()
    it = slow_gen.remote()
    first = ray_tpu.get(next(it))
    first_latency = time.time() - t0
    rest = [ray_tpu.get(r) for r in it]
    total = time.time() - t0
    assert first == 0 and rest == [1, 2]
    # first item arrived well before the whole stream finished
    assert first_latency < total - 0.25, (first_latency, total)


def test_task_streaming_large_items(ray_start):
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(3):
            yield np.full((512, 512), i, np.float32)   # 1 MB, shm path

    for i, ref in enumerate(gen.remote()):
        arr = ray_tpu.get(ref)
        assert arr.shape == (512, 512) and float(arr[0, 0]) == i


def test_task_streaming_error_mid_stream(ray_start):
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1
        raise ValueError("boom")

    it = gen.remote()
    assert ray_tpu.get(next(it)) == 1
    err_ref = next(it)
    with pytest.raises(Exception, match="boom"):
        ray_tpu.get(err_ref)
    with pytest.raises(StopIteration):
        next(it)


def test_task_streaming_backpressure(ray_start):
    """With backpressure N, the producer pauses until items are consumed."""
    @ray_tpu.remote(num_returns="streaming",
                    _generator_backpressure_num_objects=2)
    def gen():
        import time as _t
        for i in range(6):
            yield (i, _t.time())

    it = gen.remote()
    time.sleep(1.0)                  # give the producer time to run ahead
    stamps = []
    for ref in it:
        i, ts = ray_tpu.get(ref)
        stamps.append(ts)
        time.sleep(0.1)
    # later items must have been produced AFTER we started consuming:
    # without backpressure all six stamps land within the first ~50ms.
    assert stamps[-1] - stamps[0] > 0.2, stamps


def test_actor_streaming(ray_start):
    @ray_tpu.remote
    class Streamer:
        def tokens(self, n):
            for i in range(n):
                yield f"tok{i}"

    a = Streamer.remote()
    out = [ray_tpu.get(r)
           for r in a.tokens.options(num_returns="streaming").remote(4)]
    assert out == ["tok0", "tok1", "tok2", "tok3"]


def test_streaming_non_iterable_is_task_error(ray_start):
    @ray_tpu.remote(num_returns="streaming")
    def not_a_gen():
        return 42

    it = not_a_gen.remote()
    ref = next(it)
    with pytest.raises(Exception, match="generator"):
        ray_tpu.get(ref)
    with pytest.raises(StopIteration):
        next(it)


def test_streaming_abandon_cancels_producer(ray_start):
    """Breaking out of iteration cancels the producer instead of leaking
    an unbounded stream."""
    @ray_tpu.remote(num_returns="streaming",
                    _generator_backpressure_num_objects=2)
    def endless():
        i = 0
        while True:
            yield i
            i += 1

    it = endless.remote()
    first = ray_tpu.get(next(it))
    assert first == 0
    it.close()
    # The producer's worker must become reusable again (stream cancelled,
    # run_task RPC completed) — a plain task on the same pool proves it.
    @ray_tpu.remote
    def ping():
        return "ok"
    assert ray_tpu.get(ping.remote(), timeout=120) == "ok"


def test_streaming_sync_actor_serial_guarantee(ray_start):
    """A streaming method's body runs on the actor's executor: a normal
    call issued mid-stream must not interleave with it."""
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.in_gen = False

        def gen(self, n):
            self.in_gen = True
            for i in range(n):
                import time as _t
                _t.sleep(0.05)
                yield i
            self.in_gen = False

        def probe(self):
            return self.in_gen

    a = Counter.remote()
    it = a.gen.options(num_returns="streaming").remote(5)
    # probe is admitted after the stream finishes (serial executor),
    # so it must observe in_gen == False
    assert ray_tpu.get(a.probe.remote()) is False
    assert [ray_tpu.get(r) for r in it] == [0, 1, 2, 3, 4]
