"""Dependency staging: parallel arg resolution + daemon-side prefetch
(VERDICT r3 #8; reference parity: src/ray/raylet/dependency_manager.h —
args are pulled to the node while the task waits for a worker)."""

import asyncio
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.object_store import MemoryStore
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.serialization import serialize
from ray_tpu._private.worker_main import WorkerRuntime


class _SlowClient:
    """aio_get with a fixed latency: serial resolution of k args costs
    k*delay, overlapped resolution ~1*delay."""

    def __init__(self, delay: float):
        self.delay = delay
        self.memory_store = MemoryStore()

    async def aio_get(self, ref):
        await asyncio.sleep(self.delay)
        return ref.id


def test_arg_resolution_overlaps_not_serial(ray_start):
    """The latency proof: 4 ObjectRef args resolve in ~1x fetch latency,
    not 4x (the old loop awaited one ref at a time)."""
    rt = WorkerRuntime.__new__(WorkerRuntime)
    rt.client = _SlowClient(delay=0.15)
    refs = tuple(ObjectRef(f"{i:032x}", ("127.0.0.1", 1))
                 for i in range(4))
    blob = serialize((refs, {"k": refs[0]})).to_flat()

    async def run():
        t0 = time.perf_counter()
        args, kwargs = await rt._resolve_args(blob)
        return time.perf_counter() - t0, args, kwargs

    dt, args, kwargs = asyncio.new_event_loop().run_until_complete(run())
    assert args == tuple(r.id for r in refs)
    assert kwargs == {"k": refs[0].id}
    # 5 fetches x 0.15s = 0.75s serial; overlapped must stay well under
    assert dt < 0.45, f"arg resolution looks serial: {dt:.2f}s"


def test_daemon_prefetch_returns_locations(ray_start):
    """The daemon stages a task's shm-backed args while it waits for a
    worker: _prefetch_args resolves owner refs to shm locations that are
    handed to the worker via spec['_arg_locations']."""
    rt = ray_tpu.init(ignore_reinit_error=True)
    big = np.zeros(2 << 20, np.uint8)          # forced past inline limit
    ref = ray_tpu.put(big)
    spec = {"arg_refs": [(ref.id, ref.owner_addr)]}
    locs = rt.loop_runner.run_sync(
        rt.head_daemon._prefetch_args(spec), timeout=30)
    assert ref.id in locs
    assert locs[ref.id].size >= big.nbytes


def test_prefetched_multiarg_task_e2e(ray_start):
    """Scheduled-path task (custom resource pins it to a fake node) with
    multiple object args: prefetch + primed locations end-to-end."""
    node_id = ray_tpu.add_fake_node(num_cpus=2,
                                    resources={"prefetch_node": 2.0})
    try:
        arrs = [np.full(1 << 20, i, np.uint8) for i in range(3)]
        refs = [ray_tpu.put(a) for a in arrs]

        @ray_tpu.remote(num_cpus=0, resources={"prefetch_node": 1.0})
        def combine(a, b, c):
            return int(a[0]) + int(b[0]) + int(c[0])

        assert ray_tpu.get(combine.remote(*refs), timeout=60) == 3
    finally:
        ray_tpu.remove_node(node_id)
