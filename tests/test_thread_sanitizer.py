"""Runtime thread sanitizer (ISSUE 18) + engine concurrency stress.

Unit half: the sanitizer's own contracts — disarmed make_lock is a
plain threading.Lock (zero production overhead), armed locks detect
order inversions and owner re-acquisition, guarded_by descriptors
check lock ownership on reads/writes with an unguarded() escape hatch.

Stress half: the tier-1 gate the static analyzer cannot give — the
REAL engine hammered from concurrent threads (stats / lane_counts /
session_ids / abort / preempt / export_session of unknown ids) while
the pump steps 200 guarded ticks, with the sanitizer armed the whole
time. Passes only if (a) the dispatch guard sees exactly one dispatch
per tick, zero h2d uploads and zero compiles — the scrape path really
is host-only; (b) the sanitizer records ZERO violations — every
guarded-field touch held the lock; and (c) the decoded streams are
token-exact against a single-threaded oracle — concurrency changed
nothing observable.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import (EngineConfig, InferenceEngine, Request,
                         SamplingParams)
from ray_tpu.models import llama
from ray_tpu.util import thread_sanitizer as ts
from ray_tpu.util.jax_guard import dispatch_guard


@pytest.fixture(autouse=True)
def _disarm():
    yield
    ts.disarm()
    ts.reset()


# ------------------------------------------------------------- unit: locks

def test_disarmed_make_lock_is_plain_lock():
    lock = ts.make_lock("x")
    assert type(lock) is type(threading.Lock())


def test_armed_make_lock_traces():
    ts.arm()
    lock = ts.make_lock("x")
    assert isinstance(lock, ts._TracedLock)
    with lock:
        assert lock.held_by_me()
    assert not lock.held_by_me()


def test_lock_order_inversion_detected():
    ts.reset()
    ts.arm()
    a, b = ts.make_lock("a"), ts.make_lock("b")
    with a:
        with b:
            pass
    assert ts.violations() == []
    with b:
        with a:
            pass
    got = ts.violations()
    assert len(got) == 1
    assert "inversion" in got[0]
    with pytest.raises(AssertionError):
        ts.assert_clean()


def test_consistent_order_clean():
    ts.reset()
    ts.arm()
    a, b = ts.make_lock("a"), ts.make_lock("b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert ts.violations() == []


def test_owner_reacquisition_reported_not_deadlocked():
    ts.reset()
    ts.arm()
    lock = ts.make_lock("x")
    with lock:
        # a real threading.Lock would deadlock here forever; the
        # traced lock records the bug and declines the acquisition
        assert lock.acquire(timeout=0.1) is False
    got = ts.violations()
    assert len(got) == 1
    assert "re-acquisition" in got[0]


def test_strict_mode_raises_on_violating_thread():
    ts.reset()
    ts.arm(strict=True)
    lock = ts.make_lock("x")
    with lock:
        with pytest.raises(AssertionError):
            lock.acquire()
    ts.disarm()


# -------------------------------------------------------- unit: guarded_by

class _Box:
    items = ts.guarded_by("_lock")
    log = ts.guarded_by("_lock", writes_only=True)

    def __init__(self):
        self._lock = ts.make_lock("box._lock")
        with self._lock:
            self.items = []
            self.log = []


def test_guarded_field_checks_only_when_armed():
    box = _Box()          # disarmed: plain lock, no checks ever
    box.items = [1]
    assert box.items == [1]
    ts.arm()              # lock is still a plain Lock -> still no checks
    box.items = [2]
    assert ts.violations() == []


def test_guarded_field_armed_write_without_lock():
    ts.reset()
    ts.arm()
    box = _Box()
    box.items = [1]                   # unguarded write
    _ = box.items                     # unguarded read
    box.log = []                      # write-guarded too
    _ = box.log                       # ...but reads of log are free
    got = ts.violations()
    assert len(got) == 3
    assert any("write of _Box.items" in v for v in got)
    assert any("read of _Box.items" in v for v in got)
    assert any("write of _Box.log" in v for v in got)


def test_guarded_field_clean_under_lock_and_unguarded():
    ts.reset()
    ts.arm()
    box = _Box()
    with box._lock:
        box.items = [1]
        assert box.items == [1]
    with ts.unguarded():              # the blackbox crash-path escape
        assert box.items == [1]
        box.items = [2]
    assert ts.violations() == []


def test_guarded_field_wrong_thread_detected():
    ts.reset()
    ts.arm()
    box = _Box()
    hold = threading.Event()
    release = threading.Event()

    def holder():
        with box._lock:
            hold.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    hold.wait(5)
    box.items = [9]       # lock is held -- by ANOTHER thread
    release.set()
    t.join(5)
    assert any("write of _Box.items" in v for v in ts.violations())


def test_sanitized_scope_resets_and_disarms():
    with ts.sanitized():
        assert ts.armed()
        lock = ts.make_lock("y")
        with lock:
            lock.acquire(timeout=0.01)
    assert not ts.armed()
    assert len(ts.violations()) == 1   # survives for inspection
    ts.reset()
    assert ts.violations() == []


# --------------------------------------------- engine regression: snapshots

def _engine(**over):
    kw = dict(model=llama.config("debug", dtype=jnp.float32),
              max_batch_size=4, page_size=8, num_pages=160,
              prefill_buckets=(16, 32, 64), seed=7, unified_step=True)
    kw.update(over)
    return InferenceEngine(EngineConfig(**kw))


def _requests(n=3, max_tokens=256):
    rng = np.random.default_rng(11)
    return [Request(f"g{i}", rng.integers(2, 250, 12).tolist(),
                    SamplingParams(max_tokens=max_tokens))
            for i in range(n)]


def test_fleet_counters_published_snapshot():
    """fleet_counters() is the lock-free read the fleet scrape path
    uses: every mutating entry point republishes a FRESH dict (the
    old snapshot stays internally consistent for whoever holds it)."""
    eng = _engine()
    snap0 = eng.fleet_counters()
    assert snap0["waiting"] == 0 and snap0["active"] == 0
    req = _requests(1, max_tokens=16)[0]
    eng.add_request(req)
    snap1 = eng.fleet_counters()
    assert snap1 is not snap0          # replaced, not mutated
    assert snap0["waiting"] == 0       # old snapshot untouched
    assert snap1["waiting"] == 1
    while not req.finished:
        eng.step()
    snap2 = eng.fleet_counters()
    assert snap2["active"] == 0 and snap2["waiting"] == 0
    assert set(snap2) == {"active", "waiting", "parked_sessions",
                          "preemptions_total", "page_pressure", "lanes"}


def test_concurrent_adds_never_lost():
    """The race the old unlocked add_request lost: step() rebinds
    `waiting` to the survivors list mid-tick, and an append landing on
    the discarded list vanished silently. Locked add_request makes
    every add stick, whatever the interleaving."""
    eng = _engine(num_pages=256, max_batch_size=8)
    reqs = _requests(12, max_tokens=8)
    errs = []

    def pump():
        try:
            for _ in range(400):
                eng.step()
                if all(r.finished for r in reqs):
                    return
        except BaseException as exc:   # pragma: no cover
            errs.append(exc)

    t = threading.Thread(target=pump)
    t.start()
    for r in reqs:
        eng.add_request(r)
    t.join(120)
    assert not errs
    assert all(r.finished for r in reqs)
    assert all(len(r.output_tokens) == 8 for r in reqs)


def test_stats_consistent_under_concurrent_steps():
    """stats()/lane_counts() snapshot under ONE lock acquisition: no
    RuntimeError from iterating the tick deque / preempt dict
    mid-mutation, and the per-call view is internally consistent
    (lanes vs waiting counted in the same critical section)."""
    eng = _engine()
    reqs = _requests(3, max_tokens=64)
    for r in reqs:
        eng.add_request(r)
    errs = []
    stop = threading.Event()

    def scrape():
        try:
            while not stop.is_set():
                s = eng.stats()
                assert s["waiting"] >= 0
                assert s["tick_times"]["window"] >= 0
                eng.lane_counts()
                eng.session_ids()
        except BaseException as exc:
            errs.append(exc)

    threads = [threading.Thread(target=scrape, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        while not all(r.finished for r in reqs):
            eng.step()
    finally:
        stop.set()
        for t in threads:
            t.join(30)
    assert not errs, errs


# ----------------------------------------------------- the armed stress gate

def _oracle_tokens(n_req, max_tokens):
    eng = _engine()
    reqs = _requests(n_req, max_tokens)
    for r in reqs:
        eng.add_request(r)
    while not all(r.finished for r in reqs):
        eng.step()
    return {r.request_id: list(r.output_tokens) for r in reqs}


@pytest.mark.slow
def test_armed_stress_token_exact_and_clean():
    # 12-token prompts + 240 <= max_seq 256; 240 decode ticks per
    # stream keeps every request live across the whole guarded window
    n_req, max_tokens, guarded_ticks = 3, 240, 200
    want = _oracle_tokens(n_req, max_tokens)

    with ts.sanitized():
        eng = _engine()     # created armed: traced step lock
        assert isinstance(eng._step_lock, ts._TracedLock)
        reqs = _requests(n_req, max_tokens)
        for r in reqs:
            eng.add_request(r)
        # warmup: admit + prefill + settle into steady pipelined decode
        while eng.waiting or any(s.request is not None and not s.ready
                                 for s in eng.slots):
            eng.step()
        for _ in range(4):
            eng.step()

        stop = threading.Event()
        errs = []

        def hammer():
            # every lock-taking, host-only entry point the serving
            # plane exercises concurrently with the pump; unknown ids
            # so no structural event (drain/refresh) lands inside the
            # dispatch-guarded window
            try:
                while not stop.is_set():
                    eng.stats()
                    eng.lane_counts()
                    eng.session_ids()
                    eng.fleet_counters()
                    eng.has_work()
                    assert eng.abort("no-such-id") is False
                    assert eng.preempt("no-such-id") is False
                    assert eng.export_session("no-such-id") is None
            except BaseException as exc:
                errs.append(exc)

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        d0, c0 = eng.dispatches, eng.compiles
        try:
            with dispatch_guard() as rep:
                for _ in range(guarded_ticks):
                    eng.step()
        finally:
            stop.set()
            for t in threads:
                t.join(60)
        assert not errs, errs
        # ISSUE 18 acceptance: 1 dispatch/tick, 0 h2d, 0 compiles
        # while three threads hammered every scrape/abort entry point
        assert eng.dispatches - d0 == guarded_ticks
        assert eng.compiles == c0
        assert rep.n_compiles == 0
        # run the streams to completion (still armed)
        while not all(r.finished for r in reqs):
            eng.step()
        ts.assert_clean()

    got = {r.request_id: list(r.output_tokens) for r in reqs}
    assert got == want      # concurrency changed nothing observable
