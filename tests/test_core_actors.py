"""Actor semantics: creation, calls, ordering, named actors, death,
restart. Modeled on python/ray/tests/test_actor*.py."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, ActorError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, k=1):
        self.n += k
        return self.n

    def value(self):
        return self.n

    def fail(self):
        raise RuntimeError("actor method failure")

    def die(self):
        import os
        os._exit(1)


def test_actor_basic(ray_start):
    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote()) == 11
    assert ray_tpu.get(c.inc.remote(5)) == 16
    assert ray_tpu.get(c.value.remote()) == 16


def test_actor_method_ordering(ray_start):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_method_error(ray_start):
    c = Counter.remote()
    with pytest.raises(ActorError, match="actor method failure"):
        ray_tpu.get(c.fail.remote())
    # Actor still alive after a method error.
    assert ray_tpu.get(c.inc.remote()) == 1


def test_actor_init_error(ray_start):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("bad init")

        def ping(self):
            return "pong"

    b = Bad.remote()
    with pytest.raises((ActorDiedError, ActorError)):
        ray_tpu.get(b.ping.remote(), timeout=30)


def test_named_actor(ray_start):
    Counter.options(name="counter_test_named").remote(100)
    time.sleep(0.1)
    h = ray_tpu.get_actor("counter_test_named")
    assert ray_tpu.get(h.inc.remote()) == 101
    ray_tpu.kill(h)


def test_get_actor_missing(ray_start):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("never_created_actor")


def test_kill_actor(ray_start):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    time.sleep(0.5)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_crash_detected(ray_start):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    c.die.remote()
    time.sleep(1.5)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_restart(ray_start):
    @ray_tpu.remote(max_restarts=2)
    class Restartable:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    a = Restartable.remote()
    assert ray_tpu.get(a.inc.remote()) == 1
    a.die.remote()
    time.sleep(2.0)
    # After restart, state resets but the actor answers again.
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            assert ray_tpu.get(a.inc.remote(), timeout=30) == 1
            break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("actor did not come back after restart")


def test_handle_passing(ray_start):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.inc.remote(7))

    assert ray_tpu.get(bump.remote(c)) == 7
    assert ray_tpu.get(c.value.remote()) == 7


def test_async_actor(ray_start):
    @ray_tpu.remote(max_concurrency=10)
    class AsyncWorkder:
        async def work(self, t, tag):
            import asyncio
            await asyncio.sleep(t)
            return tag

    a = AsyncWorkder.remote()
    ray_tpu.get(a.work.remote(0.0, -1))   # warm up (worker spawn)
    t0 = time.time()
    refs = [a.work.remote(1.0, i) for i in range(5)]
    assert sorted(ray_tpu.get(refs)) == list(range(5))
    # Concurrent, not serial: 5 x 1s sleeps well under 4s total.
    assert time.time() - t0 < 4.0


def test_actor_concurrency_threads(ray_start):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self, t):
            time.sleep(t)
            return "ok"

    s = Sleeper.remote()
    ray_tpu.get(s.nap.remote(0.0))        # warm up (worker spawn)
    t0 = time.time()
    ray_tpu.get([s.nap.remote(1.0) for _ in range(4)])
    assert time.time() - t0 < 3.5
