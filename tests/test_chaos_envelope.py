"""Chaos under load (VERDICT r4 weak #5): SIGKILL daemon processes
mid-storm; the backlog drains, killed nodes' tasks reschedule, and the
controller never stalls. Scaled-down in-suite twin of
bench_envelope.py::bench_envelope_10x (32 daemons / 200k tasks / 4
kills there; the driver-run bench carries the envelope numbers)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def chaos_cluster():
    ray_tpu.shutdown()
    cluster = Cluster(head_cpus=4.0)
    added = [cluster.add_node(num_cpus=4.0, timeout=90)
             for _ in range(5)]
    cluster.wait_for_nodes(6)
    yield cluster, added
    cluster.shutdown()


def test_sigkill_daemons_mid_storm(chaos_cluster):
    cluster, added = chaos_cluster

    @ray_tpu.remote(max_retries=3)
    def work(i):
        time.sleep(0.002)
        return i

    n = 3000
    refs = [work.remote(i) for i in range(n)]
    time.sleep(1.0)                   # storm in flight on all nodes
    # chaos: two daemon processes die without warning
    for nid in added[:2]:
        cluster.remove_node(nid, graceful=False)
    # controller answers promptly while the wreckage reschedules
    t0 = time.time()
    from ray_tpu.util.state import list_nodes
    alive = [x for x in list_nodes() if x["alive"]]
    assert time.time() - t0 < 5.0, "controller stalled after kills"
    assert len(alive) == 4
    got = ray_tpu.get(refs, timeout=600)
    assert got == list(range(n)), "chaos lost task results"
    # survivors still schedule fresh work
    assert ray_tpu.get([work.remote(i) for i in range(50)],
                       timeout=120) == list(range(50))


def test_sigkill_node_with_actors_mid_calls(chaos_cluster):
    """Actors on a killed node surface ActorDiedError (or restart when
    allowed); actors elsewhere keep serving."""
    cluster, added = chaos_cluster

    @ray_tpu.remote(num_cpus=0.5, scheduling_strategy="SPREAD")
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

    actors = [Counter.remote() for _ in range(12)]
    homes = ray_tpu.get([a.where.remote() for a in actors], timeout=120)
    victim = added[2]
    on_victim = [a for a, h in zip(actors, homes) if h == victim]
    elsewhere = [a for a, h in zip(actors, homes) if h != victim]
    assert elsewhere, "need survivors for the assertion"
    cluster.remove_node(victim, graceful=False)
    # survivors uninterrupted
    assert all(ray_tpu.get([a.bump.remote() for a in elsewhere],
                           timeout=120))
    # victims: dead, loudly
    for a in on_victim:
        with pytest.raises(Exception):
            ray_tpu.get(a.bump.remote(), timeout=60)