"""Task semantics: submit/get/wait/errors/nesting/retries.

Modeled on reference tests python/ray/tests/test_basic*.py.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import (GetTimeoutError, InfeasibleResourceError,
                                TaskError)


def test_simple_task(ray_start):
    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get(f.remote(21)) == 42


def test_many_parallel_tasks(ray_start):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs) == [i * i for i in range(20)]


def test_task_error_propagates(ray_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("intentional")

    with pytest.raises(TaskError, match="intentional"):
        ray_tpu.get(boom.remote())


def test_object_ref_args(ray_start):
    @ray_tpu.remote
    def plus1(x):
        return x + 1

    a = plus1.remote(0)
    b = plus1.remote(a)       # ref as arg, resolved at worker
    c = plus1.remote(b)
    assert ray_tpu.get(c) == 3


def test_put_and_pass(ray_start):
    ref = ray_tpu.put({"k": [1, 2, 3]})

    @ray_tpu.remote
    def read(d):
        return d["k"][-1]

    assert ray_tpu.get(read.remote(ref)) == 3
    assert ray_tpu.get(ref) == {"k": [1, 2, 3]}


def test_large_object_roundtrip(ray_start):
    arr = np.arange(1_000_000, dtype=np.float32)   # 4MB -> shm

    @ray_tpu.remote
    def make():
        return np.arange(1_000_000, dtype=np.float32)

    out = ray_tpu.get(make.remote())
    np.testing.assert_array_equal(out, arr)

    ref = ray_tpu.put(arr * 2)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(ref)) == float((arr * 2).sum())


def test_nested_tasks(ray_start):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(0)) == 11


def test_wait(ray_start):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(3)
        return "slow"

    s, f = slow.remote(), fast.remote()
    ready, not_ready = ray_tpu.wait([s, f], num_returns=1, timeout=2.0)
    assert ready == [f]
    assert not_ready == [s]
    ready2, _ = ray_tpu.wait([s], num_returns=1)
    assert ready2 == [s]


def test_get_timeout(ray_start):
    @ray_tpu.remote
    def sleepy():
        time.sleep(5)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(sleepy.remote(), timeout=0.5)


def test_infeasible_resources(ray_start):
    @ray_tpu.remote(num_cpus=10_000)
    def f():
        return 1

    with pytest.raises(InfeasibleResourceError):
        ray_tpu.get(f.remote(), timeout=10)


def test_options_override(ray_start):
    @ray_tpu.remote(num_cpus=10_000)
    def f():
        return "ran"

    assert ray_tpu.get(f.options(num_cpus=1).remote()) == "ran"


def test_async_task_function(ray_start):
    @ray_tpu.remote
    async def afn(x):
        return x * 3

    assert ray_tpu.get(afn.remote(4)) == 12


def test_kwargs_and_defaults(ray_start):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1, c=2)) == 13


def test_cluster_resources_visible(ray_start):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU", 0) >= 8
    nodes = ray_tpu.nodes()
    assert len(nodes) >= 1 and nodes[0]["alive"]


def test_num_returns(ray_start):
    @ray_tpu.remote(num_returns=3)
    def split():
        return 1, 2, 3

    a, b, c = split.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]

    @ray_tpu.remote(num_returns=2)
    def bad():
        return 1  # not a 2-tuple

    with pytest.raises(TaskError, match="num_returns=2"):
        ray_tpu.get(bad.remote()[0])


def test_task_burst_after_actor_creation(ray_start):
    """Regression: tasks queued behind actor-occupied workers must take
    the next FREED worker, not each block on a fresh worker spawn."""
    import time

    ray_tpu = ray_start

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Holder:
        def ping(self):
            return "ok"

    ray_tpu.get([noop.remote() for _ in range(4)])
    holders = [Holder.remote() for _ in range(3)]
    assert ray_tpu.get([h.ping.remote() for h in holders]) == ["ok"] * 3

    # warm the regrown pool (one-time spawn cost), then measure
    ray_tpu.get([noop.remote() for _ in range(30)])
    t0 = time.monotonic()
    assert ray_tpu.get([noop.remote() for _ in range(100)]) == [None] * 100
    elapsed = time.monotonic() - t0
    # pre-fix this took >10s (serial spawn per waiting task)
    assert elapsed < 8.0, f"task burst took {elapsed:.1f}s"
    for h in holders:
        ray_tpu.kill(h)


def test_function_store_large_closure(ray_start):
    """Code blobs above fn_inline_limit ship once via the controller KV
    function store (fn_hash in the spec), not per-task (reference parity:
    _private/function_manager.py export + lazy import)."""
    big = bytes(range(256)) * 512        # 128 KiB captured constant

    @ray_tpu.remote
    def fat(i):
        return len(big) + i

    # Repeated calls + a second worker-side deserialize all resolve
    # through the store/cache.
    assert ray_tpu.get([fat.remote(i) for i in range(4)]) == [
        len(big) + i for i in range(4)]

    # The blob landed in the KV under its content hash.
    from ray_tpu._private.core import FN_STORE_PREFIX
    from ray_tpu._private.state import current_client
    keys = current_client().kv_keys(FN_STORE_PREFIX)
    assert keys, "expected an exported function blob in the KV store"


def test_function_store_large_actor_class(ray_start):
    table = {i: i * i for i in range(3000)}   # big captured state

    @ray_tpu.remote
    class Fat:
        def lookup(self, i):
            return table[i]

    a = Fat.remote()
    assert ray_tpu.get(a.lookup.remote(7)) == 49
