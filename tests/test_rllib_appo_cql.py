"""APPO + CQL (reference parity: rllib/algorithms/appo, rllib/algorithms/
cql — async PPO on the IMPALA architecture; conservative offline
Q-learning on the SAC machinery)."""

import numpy as np
import pytest

from ray_tpu.rllib import APPO, APPOConfig, CQL, CQLConfig, SACConfig
from ray_tpu.rllib.algorithms.dqn import _to_transitions


def test_appo_learns_cartpole():
    # num_epochs=2: the second pass over the batch is off-policy w.r.t.
    # the once-updated params, which is where the clipped surrogate
    # differs from IMPALA's plain importance-weighted loss
    algo = (APPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=64)
            .training(lr=2e-3, entropy_coeff=0.005, num_epochs=2,
                      minibatch_size=512)
            .debugging(seed=0)
            .build())
    best = 0.0
    saw_appo_loss = False
    for _ in range(60):
        m = algo.train()
        best = max(best, m["episode_return_mean"])
        # clip_fraction is emitted only by the APPO surrogate loss
        # (IMPALA's plain importance-weighted loss has no such term)
        saw_appo_loss |= np.isfinite(m.get("learner/clip_fraction",
                                           np.nan))
        if best > 80:
            break
    algo.stop()
    assert best > 80, f"APPO failed to learn: best={best}"
    assert saw_appo_loss


def _record_pendulum_transitions(out_dir, shards=4):
    """Mediocre-policy dataset: a briefly-trained SAC's rollouts."""
    from ray_tpu.rllib import SAC
    config = (SACConfig().environment("Pendulum-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                           rollout_fragment_length=64)
              .training(lr=3e-3, num_steps_before_learning=500,
                        num_updates_per_iter=8, action_scale=2.0)
              .debugging(seed=0))
    algo = config.build()
    import os
    os.makedirs(out_dir, exist_ok=True)
    for i in range(shards):
        algo.step()
        result = algo.env_runner_group.sample()
        trans = _to_transitions(result["batch"])
        np.savez(os.path.join(out_dir, f"shard-{i:05d}.npz"), **trans)
    algo.cleanup()


def test_cql_trains_offline(tmp_path):
    data = str(tmp_path / "pendulum")
    _record_pendulum_transitions(data)

    cfg = (CQLConfig().environment("Pendulum-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=64)
           .offline_data(input_path=data)
           .training(lr=1e-3, num_updates_per_iter=16,
                     train_batch_size=256, action_scale=2.0)
           .debugging(seed=0))
    cfg.cql_alpha = 2.0
    algo = cfg.build()
    m1 = algo.step()
    pen_first = m1["learner/cql_penalty"]
    pen_last = pen_first
    for _ in range(6):
        m = algo.step()
        pen_last = m["learner/cql_penalty"]
    algo.cleanup()
    assert np.isfinite(pen_first) and np.isfinite(pen_last)
    # the optimizer drives the conservative gap (OOD Q minus data Q) down
    assert pen_last < pen_first, (pen_first, pen_last)


def test_cql_requires_next_obs(tmp_path):
    d = tmp_path / "bad"
    d.mkdir()
    np.savez(d / "shard-00000.npz",
             obs=np.zeros((16, 3), np.float32),
             actions=np.zeros((16, 1), np.float32),
             rewards=np.zeros(16, np.float32),
             dones=np.zeros(16, np.float32))
    cfg = (CQLConfig().environment("Pendulum-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .offline_data(input_path=str(d)))
    with pytest.raises(ValueError, match="next_obs"):
        cfg.build()


def test_marwil_beats_bc_weighting(tmp_path):
    """MARWIL (reference: rllib/algorithms/marwil): advantage-weighted
    cloning trains from shards carrying reward-to-go; weights respond
    to advantages (mean_weight != 1) and the value head fits returns."""
    from ray_tpu.rllib import MARWIL, MARWILConfig, PPOConfig, record_samples

    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                           rollout_fragment_length=64))
    algo = config.build()
    for i in range(3):
        result = algo.env_runner_group.sample()
        record_samples(result["batch"], str(tmp_path / "data"),
                       shard_index=i, gamma=0.99)
    algo.cleanup()

    cfg = (MARWILConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=32)
           .offline_data(input_path=str(tmp_path / "data"))
           .training(lr=1e-3, num_updates_per_iter=8)
           .debugging(seed=0))
    marwil = cfg.build()
    m1 = marwil.step()
    for _ in range(4):
        m = marwil.step()
    marwil.cleanup()
    assert np.isfinite(m["learner/total_loss"])
    # value head is learning the recorded returns
    assert m["learner/vf_loss"] < m1["learner/vf_loss"], (
        m1["learner/vf_loss"], m["learner/vf_loss"])
    # advantage weighting is active (not plain BC)
    assert abs(m["learner/mean_weight"] - 1.0) > 1e-3


def test_marwil_requires_returns(tmp_path):
    d = tmp_path / "noreturns"
    d.mkdir()
    np.savez(d / "shard-00000.npz",
             obs=np.zeros((16, 4), np.float32),
             actions=np.zeros(16, np.int32),
             rewards=np.zeros(16, np.float32),
             dones=np.zeros(16, np.float32))
    from ray_tpu.rllib import MARWILConfig
    cfg = (MARWILConfig().environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .offline_data(input_path=str(d)))
    with pytest.raises(ValueError, match="returns"):
        cfg.build()
