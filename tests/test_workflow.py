"""Workflow durable execution (reference parity: python/ray/workflow —
workflow_executor.py:32): checkpointed steps, crash resume, status API."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(autouse=True)
def wf_storage(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))
    yield str(tmp_path)


def test_run_dag_and_status(ray_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(add.bind(1, 2), add.bind(3, 4))
    out = workflow.run(dag, workflow_id="wf1")
    assert out == 10
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 10
    assert ("wf1", "SUCCESSFUL") in workflow.list_all()


def test_parallel_steps_fan_out(ray_start):
    @ray_tpu.remote
    def leaf(x):
        return x * x

    @ray_tpu.remote
    def gather(*xs):
        return sum(xs)

    dag = gather.bind(*[leaf.bind(i) for i in range(4)])
    assert workflow.run(dag, workflow_id="wf-fan") == 0 + 1 + 4 + 9


def test_resume_skips_completed_steps(ray_start, wf_storage, tmp_path):
    marker = tmp_path / "exec_count"

    @ray_tpu.remote
    def counted(x):
        with open(marker, "a") as f:
            f.write("x")
        return x + 1

    @ray_tpu.remote
    def fail_once(x):
        flag = str(marker) + ".fail"
        if not os.path.exists(flag):
            with open(flag, "w") as f:
                f.write("1")
            raise RuntimeError("transient failure")
        return x * 10

    dag = fail_once.bind(counted.bind(4))
    with pytest.raises(Exception, match="transient"):
        workflow.run(dag, workflow_id="wf-resume")
    assert workflow.get_status("wf-resume") == "FAILED"
    # `counted` completed and checkpointed before the failure
    assert open(marker).read() == "x"

    out = workflow.resume("wf-resume")
    assert out == 50
    # resume did NOT re-execute the completed step
    assert open(marker).read() == "x"
    assert workflow.get_status("wf-resume") == "SUCCESSFUL"
    # resuming a finished workflow returns the cached output
    assert workflow.resume("wf-resume") == 50


def test_delete_and_not_found(ray_start):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="wf-del")
    workflow.delete("wf-del")
    assert workflow.get_status("wf-del") == "NOT_FOUND"
    with pytest.raises(ValueError):
        workflow.resume("wf-del")


def test_data_llm_batch_inference(ray_start):
    """data.llm batch inference: prompts -> generated text via the native
    engine inside a map_batches actor (reference parity:
    llm/_internal/batch/processor/vllm_engine_proc.py)."""
    import ray_tpu.data as rdata
    from ray_tpu.data.llm import (LLMEngineProcessorConfig,
                                  build_llm_processor)

    config = LLMEngineProcessorConfig(
        model_source="debug", batch_size=4, concurrency=1,
        sampling_params={"max_tokens": 8})
    processor = build_llm_processor(
        config,
        preprocess=lambda row: {"prompt": f"Q{row['q']}"},
        postprocess=lambda row: {"q": row["q"],
                                 "text": row["generated_text"],
                                 "toks": row["generated_tokens"]})
    ds = rdata.from_items([{"q": i} for i in range(4)])
    rows = processor(ds).take_all()
    assert len(rows) == 4
    for row in rows:
        assert isinstance(row["text"], str)
        assert 1 <= len(row["toks"]) <= 8


# ------------------------------------------------- dynamic continuation

def test_continuation_recursive_factorial(ray_start):
    """The verdict's bar: recursive dynamic DAGs via
    workflow.continuation (reference: workflow/api.py:776)."""
    @ray_tpu.remote
    def fact(n, acc=1):
        if n <= 1:
            return acc
        return workflow.continuation(fact.bind(n - 1, acc * n))

    assert workflow.run(fact.bind(6), workflow_id="wf-fact") == 720
    assert workflow.get_status("wf-fact") == "SUCCESSFUL"
    assert workflow.get_output("wf-fact") == 720


def test_continuation_resume_mid_expansion(ray_start, wf_storage):
    """Kill after the parent step checkpointed its continuation: resume
    re-expands and finishes without re-running completed steps."""
    runs = os.path.join(wf_storage, "runs")
    os.makedirs(runs, exist_ok=True)

    @ray_tpu.remote
    def countdown(n, mdir):
        with open(os.path.join(mdir, f"ran_{n}"), "a") as f:
            f.write("x")
        if n <= 0:
            return "done"
        if n == 2 and not os.path.exists(os.path.join(mdir, "crashed")):
            open(os.path.join(mdir, "crashed"), "w").close()
            raise RuntimeError("boom at 2")
        return workflow.continuation(countdown.bind(n - 1, mdir))

    with pytest.raises(Exception):
        workflow.run(countdown.bind(4, runs), workflow_id="wf-cd")
    assert workflow.get_status("wf-cd") == "FAILED"
    assert workflow.resume("wf-cd") == "done"
    assert workflow.get_status("wf-cd") == "SUCCESSFUL"
    # steps 4 and 3 ran exactly once (their checkpoints survived the
    # crash); step 2 ran twice (crashed once, then succeeded)
    assert len(open(os.path.join(runs, "ran_4")).read()) == 1
    assert len(open(os.path.join(runs, "ran_3")).read()) == 1
    assert len(open(os.path.join(runs, "ran_2")).read()) == 2


# -------------------------------------------------------- step options

def test_step_max_retries(ray_start, wf_storage):
    @ray_tpu.remote
    def flaky(mdir):
        p = os.path.join(mdir, "attempts")
        with open(p, "a") as f:
            f.write("x")
        if len(open(p).read()) < 3:
            raise ValueError("not yet")
        return "ok"

    dag = flaky.bind(wf_storage).options(max_retries=2)
    assert workflow.run(dag, workflow_id="wf-retry") == "ok"
    assert len(open(os.path.join(wf_storage, "attempts")).read()) == 3


def test_step_max_retries_exhausted(ray_start, wf_storage):
    @ray_tpu.remote
    def always_fails():
        raise ValueError("nope")

    dag = always_fails.bind().options(max_retries=1)
    with pytest.raises(Exception, match="always_fails"):
        workflow.run(dag, workflow_id="wf-retry-x")
    assert workflow.get_status("wf-retry-x") == "FAILED"


def test_step_catch_exceptions(ray_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("expected")

    @ray_tpu.remote
    def ok():
        return 42

    r1 = workflow.run(boom.bind().options(catch_exceptions=True),
                      workflow_id="wf-catch1")
    assert r1[0] is None and isinstance(r1[1], Exception)
    r2 = workflow.run(ok.bind().options(catch_exceptions=True),
                      workflow_id="wf-catch2")
    assert r2 == (42, None)


def test_catch_exceptions_absorbs_nonroot_substep_failure(ray_start):
    """A failure in a NON-root step of a multi-step continuation must
    route to the expanding parent's catch_exceptions policy (step ids
    are namespaced `{parent}+{n}.`; only sub-DAG roots are in the
    expansions map)."""
    @ray_tpu.remote
    def boom():
        raise ValueError("inner step failed")

    @ray_tpu.remote
    def mult(a, b):
        return a * b

    @ray_tpu.remote
    def expand():
        # boom.bind() is a NON-root dependency of the sub-DAG root
        return workflow.continuation(mult.bind(2, boom.bind()))

    result = workflow.run(expand.bind().options(catch_exceptions=True),
                          workflow_id="wf-catch-sub")
    assert result[0] is None and isinstance(result[1], Exception)

    # without a catching ancestor the same failure fails the workflow
    with pytest.raises(Exception, match="boom"):
        workflow.run(expand.bind(), workflow_id="wf-catch-sub2")
