"""Workflow durable execution (reference parity: python/ray/workflow —
workflow_executor.py:32): checkpointed steps, crash resume, status API."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(autouse=True)
def wf_storage(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))
    yield str(tmp_path)


def test_run_dag_and_status(ray_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(add.bind(1, 2), add.bind(3, 4))
    out = workflow.run(dag, workflow_id="wf1")
    assert out == 10
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 10
    assert ("wf1", "SUCCESSFUL") in workflow.list_all()


def test_parallel_steps_fan_out(ray_start):
    @ray_tpu.remote
    def leaf(x):
        return x * x

    @ray_tpu.remote
    def gather(*xs):
        return sum(xs)

    dag = gather.bind(*[leaf.bind(i) for i in range(4)])
    assert workflow.run(dag, workflow_id="wf-fan") == 0 + 1 + 4 + 9


def test_resume_skips_completed_steps(ray_start, wf_storage, tmp_path):
    marker = tmp_path / "exec_count"

    @ray_tpu.remote
    def counted(x):
        with open(marker, "a") as f:
            f.write("x")
        return x + 1

    @ray_tpu.remote
    def fail_once(x):
        flag = str(marker) + ".fail"
        if not os.path.exists(flag):
            with open(flag, "w") as f:
                f.write("1")
            raise RuntimeError("transient failure")
        return x * 10

    dag = fail_once.bind(counted.bind(4))
    with pytest.raises(Exception, match="transient"):
        workflow.run(dag, workflow_id="wf-resume")
    assert workflow.get_status("wf-resume") == "FAILED"
    # `counted` completed and checkpointed before the failure
    assert open(marker).read() == "x"

    out = workflow.resume("wf-resume")
    assert out == 50
    # resume did NOT re-execute the completed step
    assert open(marker).read() == "x"
    assert workflow.get_status("wf-resume") == "SUCCESSFUL"
    # resuming a finished workflow returns the cached output
    assert workflow.resume("wf-resume") == 50


def test_delete_and_not_found(ray_start):
    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="wf-del")
    workflow.delete("wf-del")
    assert workflow.get_status("wf-del") == "NOT_FOUND"
    with pytest.raises(ValueError):
        workflow.resume("wf-del")


def test_data_llm_batch_inference(ray_start):
    """data.llm batch inference: prompts -> generated text via the native
    engine inside a map_batches actor (reference parity:
    llm/_internal/batch/processor/vllm_engine_proc.py)."""
    import ray_tpu.data as rdata
    from ray_tpu.data.llm import (LLMEngineProcessorConfig,
                                  build_llm_processor)

    config = LLMEngineProcessorConfig(
        model_source="debug", batch_size=4, concurrency=1,
        sampling_params={"max_tokens": 8})
    processor = build_llm_processor(
        config,
        preprocess=lambda row: {"prompt": f"Q{row['q']}"},
        postprocess=lambda row: {"q": row["q"],
                                 "text": row["generated_text"],
                                 "toks": row["generated_tokens"]})
    ds = rdata.from_items([{"q": i} for i in range(4)])
    rows = processor(ds).take_all()
    assert len(rows) == 4
    for row in rows:
        assert isinstance(row["text"], str)
        assert 1 <= len(row["toks"]) <= 8
