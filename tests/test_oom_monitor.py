"""Memory monitor + worker killing policy (reference parity:
src/ray/common/memory_monitor.h:52, raylet/worker_killing_policy.h:39)."""

import time

import pytest

import ray_tpu
from ray_tpu._private.daemon import (pick_worker_to_kill,
                                     system_memory_usage)
from ray_tpu.exceptions import OutOfMemoryError


class _W:
    def __init__(self, state, spawn_time, task=None, actor_id=None):
        self.state = state
        self.spawn_time = spawn_time
        self.current_task = task
        self.actor_id = actor_id
        self.pid = 0


def test_system_memory_usage_reads_meminfo():
    used, total = system_memory_usage()
    assert 0 < used < total


def test_policy_prefers_retriable_then_newest():
    old_nonretriable = _W("busy", 1.0, {"max_retries": 0, "task_id": "a"})
    new_nonretriable = _W("busy", 3.0, {"max_retries": 0, "task_id": "b"})
    retriable = _W("busy", 2.0, {"max_retries": 2, "task_id": "c"})
    actor = _W("actor", 9.0, None, actor_id="x")
    # retriable beats newer non-retriable; actors only as last resort
    assert pick_worker_to_kill(
        [old_nonretriable, new_nonretriable, retriable, actor]) is retriable
    assert pick_worker_to_kill(
        [old_nonretriable, new_nonretriable, actor]) is new_nonretriable
    assert pick_worker_to_kill([actor]) is actor
    assert pick_worker_to_kill([_W("idle", 0.0)]) is None


def test_oom_kill_fails_task_with_oom_error():
    rt = ray_tpu.init(num_cpus=2)
    try:
        daemon = rt.head_daemon
        # drive "memory usage" above the threshold artificially
        daemon.memory_usage_fn = lambda: (99, 100)
        daemon.memory_threshold = 0.9

        @ray_tpu.remote
        def hog():
            time.sleep(60)
            return 1

        ref = hog.remote()
        with pytest.raises(OutOfMemoryError, match="memory pressure"):
            ray_tpu.get(ref, timeout=90)
        assert daemon.oom_kills >= 1
        # stop killing so shutdown is clean
        daemon.memory_usage_fn = lambda: (0, 100)
    finally:
        ray_tpu.shutdown()


def test_oom_killed_retriable_task_retries():
    rt = ray_tpu.init(num_cpus=2)
    try:
        daemon = rt.head_daemon
        kills = {"n": 0}

        def usage():
            # over-threshold exactly once: first victim dies, retry runs
            # (tasks run on leased workers via the fast path, so the
            # trigger watches both dispatch modes)
            if kills["n"] < 1 and any(
                    w.state in ("busy", "leased")
                    and (w.current_task or w.current_batch)
                    for w in daemon.workers.values()):
                kills["n"] += 1
                return (99, 100)
            return (0, 100)

        daemon.memory_usage_fn = usage
        daemon.memory_threshold = 0.9

        @ray_tpu.remote(max_retries=2)
        def flaky_mem(x):
            time.sleep(1.0)
            return x + 1

        assert ray_tpu.get(flaky_mem.remote(1), timeout=180) == 2
        assert daemon.oom_kills >= 1
    finally:
        ray_tpu.shutdown()
