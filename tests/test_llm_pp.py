"""Pipeline-parallel serving: the engine's layer stack split into stage
programs over disjoint device groups (composable with tp inside each
stage).

Reference parity: the reference reaches PP serving only by placing
external vLLM workers across PACK placement groups
(vllm_models.py:127-159); here stages are chained jit programs in one
process, activations crossing device groups via device_put (ICI on real
hardware). Gated like TP serving: greedy decode over the virtual
8-device CPU mesh must match the single-device engine token-exactly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)
from ray_tpu.models import llama
from ray_tpu.parallel import MeshSpec

PROMPTS = [[1, 2, 3, 4, 5], [9, 8, 7], [100, 101]]


def _engine(sampling=None, n_layers=None, **cfg_kwargs):
    kw = {"dtype": jnp.float32}
    if n_layers is not None:
        kw["n_layers"] = n_layers
    cfg = llama.config("debug", **kw)
    return InferenceEngine(EngineConfig(
        model=cfg, max_batch_size=4, num_pages=64, seed=3, **cfg_kwargs))


def _generate(sampling=None, **cfg_kwargs):
    eng = _engine(**cfg_kwargs)
    reqs = eng.generate([list(p) for p in PROMPTS],
                        sampling or SamplingParams(max_tokens=8))
    return [r.output_tokens for r in reqs]


def test_pp2_decode_matches_single_device():
    ref = _generate()
    pp2 = _generate(mesh=MeshSpec(tp=1, fsdp=1, pp=2))
    assert pp2 == ref


def test_tp2_pp2_decode_matches_single_device():
    ref = _generate()
    both = _generate(mesh=MeshSpec(tp=2, fsdp=1, pp=2))
    assert both == ref


def test_pp2_chunked_prefill_matches():
    """A prompt longer than max_prefill_tokens prefills chunk-by-chunk
    through every stage (cached-context attention per stage slice)."""
    long_prompt = np.random.default_rng(5).integers(
        1, 250, 40).tolist()

    def gen(mesh):
        eng = _engine(mesh=mesh, max_prefill_tokens=16)
        [req] = eng.generate([list(long_prompt)],
                             SamplingParams(max_tokens=6))
        return req.output_tokens

    assert gen(MeshSpec(tp=1, fsdp=1, pp=2)) == gen(None)


def test_pp2_penalty_sampling_path():
    """Repetition penalty exercises the seen-state on the LAST stage
    (the non-greedy program variant); greedy temp=0 keeps it exact."""
    s = SamplingParams(max_tokens=8, repetition_penalty=1.3)
    ref = _generate(sampling=s)
    pp2 = _generate(sampling=s, mesh=MeshSpec(tp=1, fsdp=1, pp=2))
    assert pp2 == ref


def test_pp2_prefix_cache_round_trip():
    """Prefix caching shares pages across requests under pp (page ids
    are global; only the pools are layer-split)."""
    prompt = np.random.default_rng(7).integers(1, 250, 34).tolist()
    eng = _engine(mesh=MeshSpec(tp=1, fsdp=1, pp=2),
                  max_prefill_tokens=16)
    [a] = eng.generate([list(prompt)], SamplingParams(max_tokens=5))
    [b] = eng.generate([list(prompt)], SamplingParams(max_tokens=5))
    assert eng.allocator.cache_hit_tokens > 0
    assert a.output_tokens == b.output_tokens


def test_pp_rejects_lora():
    eng = _engine(mesh=MeshSpec(tp=1, fsdp=1, pp=2))
    r = 2
    adapters = {"wq": (np.zeros((2, 32, r), np.float32),
                       np.zeros((2, r, 32), np.float32))}
    with pytest.raises(NotImplementedError):
        eng.register_lora("a", adapters)


def test_pp_validates_layer_divisibility():
    with pytest.raises(ValueError, match="divisible by pp"):
        _engine(n_layers=3, mesh=MeshSpec(tp=1, fsdp=1, pp=2))


def test_pp2_overlapped_decode_matches_single_device():
    """Overlapped pp decode (VERDICT r4 weak #6): microbatched stage
    chains — token-exact vs both the sequential pp path and the
    single-device engine."""
    ref = _generate()
    seq = _generate(mesh=MeshSpec(tp=1, fsdp=1, pp=2))
    over = _generate(mesh=MeshSpec(tp=1, fsdp=1, pp=2),
                     pp_decode_microbatches=2)
    assert over == seq == ref


def test_pp2_overlapped_with_sampling_and_penalties():
    """Sampled decode through the overlapped path: per-microbatch RNG
    streams differ from the full-batch split by construction (greedy
    exactness is the cross-path gate above), so the guarantees here are
    completion + same-seed determinism."""
    sampling = SamplingParams(max_tokens=8, temperature=0.7, top_k=20,
                              repetition_penalty=1.2)
    over1 = _generate(sampling, mesh=MeshSpec(tp=1, fsdp=1, pp=2),
                      pp_decode_microbatches=2)
    over2 = _generate(sampling, mesh=MeshSpec(tp=1, fsdp=1, pp=2),
                      pp_decode_microbatches=2)
    assert all(len(o) == 8 for o in over1)
    assert over1 == over2


def test_pp_overlap_validation():
    with pytest.raises(ValueError, match="pp>1"):
        _engine(pp_decode_microbatches=2)
    with pytest.raises(ValueError, match="divide"):
        _engine(mesh=MeshSpec(tp=1, fsdp=1, pp=2),
                pp_decode_microbatches=3)
