"""Tune: search spaces, controller, schedulers (ASHA/PBT), function and
class trainables. Modeled on python/ray/tune/tests."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (ASHAScheduler, BasicVariantGenerator,
                          ConcurrencyLimiter, HyperBandScheduler,
                          MedianStoppingRule, PopulationBasedTraining,
                          Trainable, TuneConfig, Tuner)
from ray_tpu.tune.search.variant_generator import generate_variants


# -- search-space resolution (no cluster needed) ---------------------------

def test_grid_and_sample_resolution():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0.0, 1.0),
        "depth": tune.grid_search([2, 4]),
        "nested": {"units": tune.choice([32, 64])},
    }
    variants = list(generate_variants(space, np.random.default_rng(0)))
    assert len(variants) == 4  # 2 x 2 grid
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    for v in variants:
        assert 0.0 <= v["wd"] < 1.0
        assert v["nested"]["units"] in (32, 64)


def test_sample_from_sees_spec():
    space = {
        "a": tune.grid_search([3, 5]),
        "b": tune.sample_from(lambda spec: spec.config.a * 10),
    }
    variants = list(generate_variants(space, np.random.default_rng(0)))
    assert sorted(v["b"] for v in variants) == [30, 50]


def test_loguniform_and_randint_bounds():
    rng = np.random.default_rng(0)
    for _ in range(100):
        assert 1e-5 <= tune.loguniform(1e-5, 1e-1).sample(rng) <= 1e-1
        assert 2 <= tune.randint(2, 9).sample(rng) < 9
        assert tune.qrandint(0, 100, 10).sample(rng) % 10 == 0


# -- end-to-end experiments ------------------------------------------------

def _objective(config):
    score = 0.0
    for step in range(5):
        score += config["lr"]
        tune.report({"score": score, "step": step})


def test_function_trainable_grid(ray_start):
    results = tune.run(_objective,
                       config={"lr": tune.grid_search([0.1, 0.5, 1.0])},
                       metric="score", mode="max")
    assert len(results) == 3
    best = results.get_best_result()
    assert best.config["lr"] == 1.0
    assert best.metrics["score"] == pytest.approx(5.0)


class _StepTrainable(Trainable):
    def setup(self, config):
        self.value = 0.0

    def step(self):
        self.value += self.config["delta"]
        return {"value": self.value}

    def save_checkpoint(self):
        return {"value": self.value}

    def load_checkpoint(self, state):
        self.value = state["value"]


def test_class_trainable_asha_stops_bad_trials(ray_start):
    tuner = Tuner(
        _StepTrainable,
        # Descending order: weak trials reach each rung after strong ones
        # have set the cutoff (async halving cuts on arrival).
        param_space={"delta": tune.grid_search([2.0, 1.0, 0.2, 0.1])},
        tune_config=TuneConfig(
            metric="value", mode="max", max_concurrent_trials=4,
            scheduler=ASHAScheduler(max_t=12, grace_period=2,
                                    reduction_factor=2)))
    results = tuner.fit()
    best = results.get_best_result()
    assert best.config["delta"] == 2.0
    # ASHA must have cut at least one weak trial before max_t
    iters = [r.metrics.get("training_iteration", 0) for r in results.results]
    assert min(iters) < 12 and max(iters) == 12


def test_median_stopping(ray_start):
    results = tune.run(
        _StepTrainable,
        config={"delta": tune.grid_search([0.01, 1.0, 1.1, 1.2])},
        metric="value", mode="max", stop={"training_iteration": 10},
        scheduler=MedianStoppingRule(grace_period=2,
                                     min_samples_required=2))
    by_delta = {r.config["delta"]: r for r in results.results}
    slow = by_delta[0.01].metrics.get("training_iteration", 99)
    fast = by_delta[1.2].metrics.get("training_iteration", 0)
    assert slow <= fast


def test_pbt_exploits_and_perturbs(ray_start):
    scheduler = PopulationBasedTraining(
        metric="value", mode="max", perturbation_interval=2,
        hyperparam_mutations={"delta": tune.uniform(0.5, 3.0)}, seed=0)
    tuner = Tuner(
        _StepTrainable,
        param_space={"delta": tune.grid_search([0.01, 0.02, 2.0, 3.0])},
        tune_config=TuneConfig(metric="value", mode="max",
                               max_concurrent_trials=4,
                               scheduler=scheduler,
                               time_budget_s=60))
    # Cap experiment length: stop everything at iteration 8 via ASHA-less
    # trainable done flag — use tune.run max_t through scheduler instead.
    class Capped(_StepTrainable):
        def step(self):
            result = super().step()
            result["done"] = self._iteration >= 7
            return result
    tuner._trainable = Capped
    results = tuner.fit()
    assert scheduler.num_perturbations >= 1
    best = results.get_best_result()
    assert best.metrics["value"] > 2.0


def test_concurrency_limiter(ray_start):
    searcher = ConcurrencyLimiter(
        BasicVariantGenerator({"lr": tune.uniform(0, 1)}, num_samples=5,
                              seed=1, metric="score", mode="max"),
        max_concurrent=2)
    results = tune.run(_objective, search_alg=searcher, metric="score",
                       mode="max", max_concurrent_trials=4)
    assert len(results) == 5


def test_trial_error_surfaces(ray_start):
    def bad(config):
        raise ValueError("boom")

    results = tune.run(bad, config={}, metric="x", mode="max")
    assert len(results.errors) == 1
    assert "boom" in results.errors[0]


def test_hyperband_promotes(ray_start):
    results = tune.run(
        _StepTrainable,
        config={"delta": tune.grid_search([0.1, 0.5, 1.0, 2.0])},
        metric="value", mode="max",
        scheduler=HyperBandScheduler(max_t=9, reduction_factor=3))
    best = results.get_best_result()
    assert best.config["delta"] == 2.0


def test_pb2_gp_directed_explore(ray_start):
    """PB2 (reference: tune/schedulers/pb2.py): exploit configs come
    from the GP-UCB bandit within hyperparam_bounds, not random
    perturbation; the experiment still improves the population."""
    from ray_tpu.tune import PB2

    scheduler = PB2(
        metric="value", mode="max", perturbation_interval=2,
        hyperparam_bounds={"delta": (0.5, 3.0)}, seed=0)

    class Capped(_StepTrainable):
        def step(self):
            result = super().step()
            result["done"] = self._iteration >= 7
            return result

    tuner = Tuner(
        Capped,
        param_space={"delta": tune.grid_search([0.01, 0.02, 2.0, 3.0])},
        tune_config=TuneConfig(metric="value", mode="max",
                               max_concurrent_trials=4,
                               scheduler=scheduler,
                               time_budget_s=60))
    results = tuner.fit()
    assert scheduler.num_perturbations >= 1
    # every exploited config stays inside the declared bounds
    for _, (_, cfg) in list(scheduler.pending_exploits.items()):
        assert 0.5 <= cfg["delta"] <= 3.0
    assert results.get_best_result().metrics["value"] > 2.0


def test_pb2_gp_math():
    """The internal GP interpolates a smooth function and UCB prefers
    the known-good region once data exists."""
    import numpy as np
    from ray_tpu.tune.schedulers.pb2 import _GP

    rng = np.random.default_rng(0)
    x = rng.uniform(size=(40, 2))
    y = np.sin(3 * x[:, 1]) + 0.01 * rng.normal(size=40)
    gp = _GP()
    gp.fit(x, (y - y.mean()) / y.std())
    q = np.array([[0.5, 0.5], [0.5, 0.52]])
    mu, sd = gp.predict(q)
    assert np.all(sd >= 0)
    # interpolation: prediction close to the true (normalized) function
    true = (np.sin(3 * q[:, 1]) - y.mean()) / y.std()
    assert np.all(np.abs(mu - true) < 0.35), (mu, true)
