"""Learning-regression baselines for the search stack (VERDICT r3 #9).

The reference treats rllib/tuned_examples + tuned search suites as
regression tests: an "intelligent" searcher must actually BEAT random
search at matched budget on a known surface, not just run. These drive
the searchers directly (suggest/observe loop — no cluster), paired-seed
against RandomSearch on the Branin function, the classic 2-D benchmark
(global min 0.397887).
"""

import math

import numpy as np
import pytest

from ray_tpu.tune.search.sample import uniform
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.tpe import TPESearch
from ray_tpu.tune.search.bohb import BOHBSearch


def branin(x1: float, x2: float) -> float:
    a, b, c = 1.0, 5.1 / (4 * math.pi ** 2), 5.0 / math.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * math.pi)
    return (a * (x2 - b * x1 ** 2 + c * x1 - r) ** 2
            + s * (1 - t) * math.cos(x1) + s)


SPACE = {"x1": uniform(-5.0, 10.0), "x2": uniform(0.0, 15.0)}


def _drive(searcher, budget: int, observe_fn) -> float:
    """suggest -> evaluate -> observe loop; returns best value found."""
    best = float("inf")
    for i in range(budget):
        cfg = searcher.suggest(f"t{i}")
        if cfg is None or cfg is Searcher.FINISHED:
            break
        val = branin(cfg["x1"], cfg["x2"])
        best = min(best, val)
        observe_fn(searcher, f"t{i}", cfg, val)
    return best


def _random_best(seed: int, budget: int) -> float:
    rng = np.random.default_rng(seed)
    return min(branin(SPACE["x1"].sample(rng), SPACE["x2"].sample(rng))
               for _ in range(budget))


def test_tpe_beats_random_on_branin():
    budget, seeds = 64, [0, 1, 2, 3, 4]

    def observe(s, tid, cfg, val):
        s.on_trial_complete(tid, {"loss": val})

    tpe_best = [_drive(TPESearch(SPACE, metric="loss", mode="min",
                                 num_samples=budget, seed=seed),
                       budget, observe)
                for seed in seeds]
    rnd_best = [_random_best(seed, budget) for seed in seeds]
    wins = sum(t < r for t, r in zip(tpe_best, rnd_best))
    assert np.mean(tpe_best) < np.mean(rnd_best), (tpe_best, rnd_best)
    assert wins >= 3, (tpe_best, rnd_best)
    # and it actually gets close to the optimum
    assert np.mean(tpe_best) < 1.5, tpe_best


def test_bohb_beats_random_on_branin_with_budgets():
    """BOHB observes results at multiple fidelity levels; the top budget
    drives the model. Simulated fidelity: noisy at iter 1, exact at 3."""
    budget, seeds = 64, [0, 1, 2]

    def observe(s, tid, cfg, val):
        # crc32, not hash(): str hashing is salted per process
        # (PYTHONHASHSEED), which made the noise — and occasionally the
        # verdict — vary across pytest runs
        import zlib
        noisy = val + np.random.default_rng(
            zlib.crc32(str(tid).encode()) % 2 ** 31).normal(0, 2.0)
        s.on_trial_result(tid, {"loss": noisy, "training_iteration": 1})
        s.on_trial_complete(
            tid, {"loss": val, "training_iteration": 3})

    bohb_best = [_drive(BOHBSearch(SPACE, metric="loss", mode="min",
                                   num_samples=budget, seed=seed),
                        budget, observe)
                 for seed in seeds]
    rnd_best = [_random_best(seed, budget) for seed in seeds]
    assert np.mean(bohb_best) < np.mean(rnd_best), (bohb_best, rnd_best)
    assert np.mean(bohb_best) < 2.0, bohb_best


def test_pb2_tracks_moving_optimum_beats_random(ray_start):
    """PB2's GP-directed explore must track a drifting optimum better
    than a static random population at matched budget (the PBT
    tuned-example discipline, scaled down)."""
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.tune import PB2

    def reward(lr: float, t: int) -> float:
        target = 0.2 + 0.06 * t          # drifts upward over time
        return -abs(lr - target)

    def trainable(config):
        lr = config["lr"]
        for t in range(8):
            lr = config["lr"]            # PB2 rewrites config on exploit
            tune.report(score=reward(lr, t), training_iteration=t + 1)

    def run_with(scheduler, seed):
        tuner = tune.Tuner(
            trainable,
            param_space={"lr": uniform(0.0, 1.0)},
            tune_config=tune.TuneConfig(
                metric="score", mode="max", num_samples=6,
                scheduler=scheduler, seed=seed),
        )
        grid = tuner.fit()
        return max(r.metrics.get("score", -9e9) for r in grid)

    pb2_final, rnd_final = [], []
    for seed in (0, 1):
        pb2_final.append(run_with(
            PB2(metric="score", mode="max", perturbation_interval=2,
                hyperparam_bounds={"lr": [0.0, 1.0]}, seed=seed), seed))
        rnd_final.append(run_with(None, seed))
    assert np.mean(pb2_final) >= np.mean(rnd_final) - 1e-9, (
        pb2_final, rnd_final)
