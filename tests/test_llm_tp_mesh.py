"""Pod-scale data plane (ISSUE 17): the whole serving engine
shard_map'd over a named (data, tp) mesh.

Unlike test_llm_tp.py's GSPMD path (mesh=MeshSpec, compiler-inferred
sharding), EngineConfig.mesh_shape builds an EXPLICIT Megatron
program: KV pools and weights sharded over heads along `tp`, page
tables and sampling state replicated, logits reduced with lax.psum
(or quantized_psum). The gates here are the acceptance criteria:
token-exactness against the single-chip oracle (greedy AND sampled,
including a preempt/restore cycle), the one-dispatch-per-tick
discipline at tp=2, KV movement across topologies, and per-chip perf
accounting. Everything runs on the conftest's emulated 8-device CPU
mesh (`xla_force_host_platform_device_count`).
"""

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)
from ray_tpu.models import llama
from ray_tpu.ops import tp_mesh
from ray_tpu.parallel import MeshSpec

PROMPTS = [[1, 2, 3, 4, 5], [9, 8, 7], [100, 101]]

# shared engine shape for the KV-movement gates: small pages so a
# 12-token prompt spans several, forcing real gather/scatter traffic
_COMMON = dict(max_batch_size=3, page_size=8, num_pages=64,
               prefill_buckets=(16, 32, 64), max_prefill_tokens=16,
               seed=9)


def _mk(**kw):
    cfg = llama.config("debug", dtype=jnp.float32)
    return InferenceEngine(EngineConfig(model=cfg, **_COMMON, **kw))


def _drain(eng):
    while eng.has_work():
        eng.step()


def _gen(sp, **kw):
    cfg = llama.config("debug", dtype=jnp.float32)
    eng = InferenceEngine(EngineConfig(
        model=cfg, max_batch_size=4, num_pages=64, seed=3, **kw))
    reqs = eng.generate([list(p) for p in PROMPTS], sp)
    return [r.output_tokens for r in reqs], eng


# -- mesh construction ---------------------------------------------------

def test_parse_mesh_shape():
    assert tp_mesh.parse_mesh_shape("1x2") == (1, 2)
    assert tp_mesh.parse_mesh_shape("1,4") == (1, 4)
    assert tp_mesh.parse_mesh_shape("2") == (1, 2)
    with pytest.raises(ValueError):
        tp_mesh.parse_mesh_shape("banana")


def test_build_serving_mesh():
    mesh = tp_mesh.build_serving_mesh((1, 2))
    assert mesh.axis_names == (tp_mesh.DATA_AXIS, "tp")
    assert tp_mesh.mesh_chips(mesh) == 2


def test_build_serving_mesh_rejects_data_parallel():
    with pytest.raises(ValueError, match="data parallelism"):
        tp_mesh.build_serving_mesh((2, 1))


def test_build_serving_mesh_rejects_axis_collision():
    with pytest.raises(ValueError):
        tp_mesh.build_serving_mesh((1, 2), tp_axis=tp_mesh.DATA_AXIS)


def test_build_serving_mesh_rejects_too_many_devices():
    with pytest.raises(ValueError):
        tp_mesh.build_serving_mesh((1, 1024))


# -- engine config validation --------------------------------------------

def test_mesh_shape_and_mesh_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        _mk(mesh_shape=(1, 2), mesh=MeshSpec(tp=2))


def test_mesh_shape_rejects_moe():
    cfg = llama.config("debug_moe", dtype=jnp.float32)
    with pytest.raises(ValueError, match="MoE"):
        InferenceEngine(EngineConfig(model=cfg, **_COMMON,
                                     mesh_shape=(1, 2)))


def test_mesh_shape_rejects_nondivisible_heads():
    # debug has n_kv_heads=2: tp=4 can't split them
    with pytest.raises(ValueError, match="not divisible"):
        _mk(mesh_shape=(1, 4))


def test_mesh_shape_rejects_lora():
    eng = _mk(mesh_shape=(1, 2))
    with pytest.raises(NotImplementedError, match="LoRA"):
        eng.register_loras({})


def test_mesh_shape_one_chip_is_plain_engine():
    eng = _mk(mesh_shape=(1, 1))
    assert eng.n_chips == 1
    reqs = eng.generate([[1, 2, 3]], SamplingParams(max_tokens=4))
    assert len(reqs[0].output_tokens) == 4


# -- token-exactness vs the single-chip oracle ---------------------------

@pytest.mark.parametrize("sp", [
    SamplingParams(max_tokens=8),
    SamplingParams(max_tokens=8, temperature=0.9, top_p=0.95,
                   seed=11),
], ids=["greedy", "sampled"])
def test_tp2_token_exact_vs_single_chip(sp):
    """The sharded tick is the SAME program as the single-chip tick:
    f32 compute makes the psum reduction order immaterial, so tokens
    must match bit-for-bit — greedy and seeded-sampled alike."""
    ref, e1 = _gen(sp)
    tp2, e2 = _gen(sp, mesh_shape=(1, 2))
    assert (e1.n_chips, e2.n_chips) == (1, 2)
    assert tp2 == ref
    assert e2.stats()["chips"] == 2


def test_tp2_perf_accounting_is_per_chip():
    """stats()['perf'] divides the analytic envelope by the mesh
    size: the accountant's peak is peak_flops x n_chips, so the
    reported mfu/mbu are per chip against the 0.40 target."""
    _, e1 = _gen(SamplingParams(max_tokens=8))
    _, e2 = _gen(SamplingParams(max_tokens=8), mesh_shape=(1, 2))
    p1, p2 = e1.stats()["perf"], e2.stats()["perf"]
    assert (p1["n_chips"], p2["n_chips"]) == (1, 2)
    assert p2["peak_flops"] == pytest.approx(2 * p1["peak_flops"])
    assert 0.0 <= p2["mfu"] <= 1.0


def test_tp2_quantized_collectives_generates():
    """quantized_collectives=True routes the logits psum through
    ops.quantized_collectives.quantized_psum — tokens may differ
    from the exact-f32 reduction, but the engine must run clean."""
    eng = _mk(mesh_shape=(1, 2), quantized_collectives=True,
              unified_step=True, async_readback=True)
    reqs = eng.generate([[1, 2, 3, 4, 5]], SamplingParams(max_tokens=8))
    assert len(reqs[0].output_tokens) == 8


# -- dispatch discipline at tp>1 -----------------------------------------

@pytest.mark.parametrize("kv", ["f32", "int8"])
def test_tp2_steady_state_one_dispatch_per_tick(kv):
    """32 ticks = 32 dispatches, 0 host transfers, 0 compiles: the
    shard_map'd collective-bearing tick keeps the single-dispatch
    discipline (donation + async readback) the single-chip engine
    has, for raw and quantized KV alike."""
    eng = _mk(mesh_shape=(1, 2), kv_dtype=kv, unified_step=True,
              async_readback=True)
    for i in range(3):
        eng.add_request(Request(request_id=f"r{i}",
                                prompt_tokens=list(range(1, 13)),
                                params=SamplingParams(max_tokens=64)))
    for _ in range(6):          # warm: prefill + first decode ticks
        eng.step()
    d0, c0 = eng.dispatches, eng.compiles
    with jax.transfer_guard("disallow"):
        for _ in range(32):
            eng.step()
    assert eng.dispatches - d0 == 32
    assert eng.compiles - c0 == 0


# -- KV movement across topologies ---------------------------------------

def test_tp2_spill_restore_token_exact():
    """A preempt/restore (host spill) cycle mid-stream on the tp=2
    engine must not perturb a sampled stream: token-exact vs a
    never-preempted single-chip oracle."""
    e0 = _mk()
    r0 = Request("a", list(range(1, 13)),
                 SamplingParams(max_tokens=20, temperature=0.8,
                                seed=7))
    e0.add_request(r0)
    _drain(e0)

    e1 = _mk(mesh_shape=(1, 2), enable_kv_offload=True)
    r1 = Request("a", list(range(1, 13)),
                 SamplingParams(max_tokens=20, temperature=0.8,
                                seed=7))
    e1.add_request(r1)
    for _ in range(6):
        e1.step()
    assert e1.preempt("a", reason="test")
    _drain(e1)
    assert r1.output_tokens == r0.output_tokens


def test_tp2_export_imports_into_tp1_token_exact():
    """Session wire format is topology-free: export gathers the full
    global KV (int8 pages + scales), so a tp=2 export resumes on a
    tp=1 engine with identical continuation tokens."""
    e2 = _mk(mesh_shape=(1, 2), kv_dtype="int8",
             enable_kv_offload=True)
    r2 = Request("m", list(range(1, 13)),
                 SamplingParams(max_tokens=20))
    e2.add_request(r2)
    for _ in range(6):
        e2.step()
    assert e2.preempt("m", reason="ship")
    state = e2.export_session("m")
    # full global shape, not a shard: (layers, pages, page, kv_heads, hd)
    assert state["k"].shape[3] == llama.config("debug").n_kv_heads

    e3 = _mk(kv_dtype="int8", enable_kv_offload=True)
    imported = e3.import_session(state)
    _drain(e3)

    e4 = _mk(kv_dtype="int8")
    r4 = Request("m", list(range(1, 13)),
                 SamplingParams(max_tokens=20))
    e4.add_request(r4)
    _drain(e4)
    assert imported.output_tokens == r4.output_tokens


def test_tp2_export_kind_mismatch_degrades_to_replay():
    """An int8 tp=2 export offered to an f32 engine must raise
    ValueError (the fleet's replay-fallback signal), never crash or
    silently reinterpret the payload."""
    e2 = _mk(mesh_shape=(1, 2), kv_dtype="int8",
             enable_kv_offload=True)
    e2.add_request(Request("m", list(range(1, 13)),
                           SamplingParams(max_tokens=20)))
    for _ in range(6):
        e2.step()
    assert e2.preempt("m", reason="ship")
    state = e2.export_session("m")
    e5 = _mk(enable_kv_offload=True)      # f32 KV
    with pytest.raises(ValueError):
        e5.import_session(dict(state))
