"""Declarative serve deploy (YAML/schema) + local testing mode.

Reference parity targets: serve/schema.py ServeDeploySchema,
serve/scripts.py `serve deploy`, serve/_private/local_testing_mode.py.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))   # serve_test_app importable

from ray_tpu import serve
from ray_tpu.serve.schema import (DeploymentSchema, ServeApplicationSchema,
                                  ServeDeploySchema, build_app_from_schema)


# ----------------------------------------------------------- local testing

def test_local_testing_mode_no_cluster():
    """Handles work with NO cluster: composition, methods, streaming."""
    @serve.deployment
    class Child:
        def __call__(self, x):
            return x * 2

        def describe(self):
            return "child"

    @serve.deployment
    class Parent:
        def __init__(self, child):
            self.child = child

        async def __call__(self, x):
            return await self.child.remote(x) + 1

        def stream(self, n):
            for i in range(n):
                yield i * 10

    h = serve.run(Parent.bind(Child.bind()), name="local-app",
                  local_testing_mode=True)
    assert h.remote(5).result() == 11
    # direct child handle + non-default method
    ch = serve.get_deployment_handle("Child", "local-app")
    assert ch.remote(3).result() == 6
    assert ch.describe.remote().result() == "child"
    # streaming
    items = list(h.options(method_name="stream", stream=True).remote(3))
    assert items == [0, 10, 20]
    serve.shutdown()


def test_local_testing_mode_init_errors_raise_eagerly():
    @serve.deployment
    class Broken:
        def __init__(self):
            raise RuntimeError("constructor boom")

    with pytest.raises(RuntimeError, match="constructor boom"):
        serve.run(Broken.bind(), name="broken-app",
                  local_testing_mode=True)
    serve.shutdown()


def test_local_testing_user_config():
    @serve.deployment(user_config={"k": 7})
    class Cfg:
        def __init__(self):
            self.k = 0

        def reconfigure(self, cfg):
            self.k = cfg["k"]

        def __call__(self):
            return self.k

    h = serve.run(Cfg.bind(), name="cfg-local", local_testing_mode=True)
    assert h.remote().result() == 7
    serve.shutdown()


# ----------------------------------------------------------------- schema

def test_schema_validation():
    with pytest.raises(ValueError, match="import_path"):
        ServeApplicationSchema.from_dict({"name": "x"})
    with pytest.raises(ValueError, match="unknown application"):
        ServeApplicationSchema.from_dict(
            {"import_path": "a:b", "bogus": 1})
    with pytest.raises(ValueError, match="duplicate application"):
        ServeDeploySchema.from_dict({"applications": [
            {"import_path": "a:b", "name": "x"},
            {"import_path": "c:d", "name": "x"}]})
    with pytest.raises(ValueError, match="duplicate route_prefix"):
        ServeDeploySchema.from_dict({"applications": [
            {"import_path": "a:b", "name": "x", "route_prefix": "/"},
            {"import_path": "c:d", "name": "y", "route_prefix": "/"}]})
    # null route_prefix never collides
    s = ServeDeploySchema.from_dict({"applications": [
        {"import_path": "a:b", "name": "x", "route_prefix": None},
        {"import_path": "c:d", "name": "y", "route_prefix": None}]})
    assert len(s.applications) == 2
    with pytest.raises(ValueError, match="needs a 'name'"):
        DeploymentSchema.from_dict({"num_replicas": 2})


def test_build_app_from_schema_overrides_and_builder():
    app = build_app_from_schema(ServeApplicationSchema(
        import_path="serve_test_app:app",
        deployments=[DeploymentSchema(name="Doubler", num_replicas=2)]))
    # find the Doubler node and check the override landed
    child = app._args[0]
    assert child._deployment.config.num_replicas == 2
    # typo'd override name must raise, not silently no-op
    with pytest.raises(ValueError, match="match no deployment"):
        build_app_from_schema(ServeApplicationSchema(
            import_path="serve_test_app:app",
            deployments=[DeploymentSchema(name="Dublor")]))
    # builder function + args
    b = build_app_from_schema(ServeApplicationSchema(
        import_path="serve_test_app:build_app", args={"bias": 5}))
    h = serve.run(b, name="builder-local", local_testing_mode=True)
    assert h.remote(1).result() == 6
    serve.shutdown()


# ------------------------------------------------------------- YAML deploy

def test_yaml_deploy_e2e(ray_start, tmp_path):
    cfg = tmp_path / "app.yaml"
    cfg.write_text("""
applications:
  - name: yaml-app
    route_prefix: /yaml
    import_path: serve_test_app:app
    deployments:
      - name: Doubler
        num_replicas: 1
      - name: Gateway
        max_ongoing_requests: 4
""")
    try:
        handles = serve.deploy_config(str(cfg))
        assert set(handles) == {"yaml-app"}
        assert handles["yaml-app"].remote(4).result() == 9
        st = serve.status()
        assert st["applications"]["yaml-app"]["status"] == "RUNNING"
    finally:
        serve.shutdown()


def test_overrides_reach_container_nested_deployments():
    """Applications bound inside list/dict args get overrides and
    runtime_env folding too (shared map_deployments walker)."""
    @serve.deployment
    class Leaf:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Fan:
        def __init__(self, children):
            self.children = children

        async def __call__(self, x):
            out = x
            for c in self.children:
                out = await c.remote(out)
            return out

    from ray_tpu.serve.schema import _apply_overrides
    app = Fan.bind([Leaf.bind()])
    out = _apply_overrides(
        app, {"Leaf": DeploymentSchema(name="Leaf", num_replicas=3)})
    leaf = out._args[0][0]
    assert leaf._deployment.config.num_replicas == 3
    from ray_tpu.serve.api import _fold_runtime_env
    folded = _fold_runtime_env(app, {"env_vars": {"A": "1"}})
    leaf2 = folded._args[0][0]
    assert leaf2._deployment.config.ray_actor_options[
        "runtime_env"] == {"env_vars": {"A": "1"}}
    # and the graph still works end-to-end in local mode
    h = serve.run(out, name="fan-local", local_testing_mode=True)
    assert h.remote(1).result() == 2
    serve.shutdown()
