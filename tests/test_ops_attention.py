"""Attention kernels vs reference (CPU mesh; pallas in interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (flash_attention, reference_attention,
                         ring_attention_sharded)
from ray_tpu.parallel import MeshSpec


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    B, S, H, KVH, D = 2, 256, 4, 2, 64
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, KVH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, KVH, D)), jnp.float32)
    return q, k, v


def test_flash_matches_reference_causal(qkv):
    q, k, v = qkv
    ref = reference_attention(q, k, v, causal=True)
    fl = flash_attention(q, k, v, True, None, 128, 128, True)
    assert jnp.allclose(ref, fl, atol=2e-5)


def test_flash_matches_reference_noncausal(qkv):
    q, k, v = qkv
    ref = reference_attention(q, k, v, causal=False)
    fl = flash_attention(q, k, v, False, None, 128, 128, True)
    assert jnp.allclose(ref, fl, atol=2e-5)


def test_flash_gradients(qkv):
    q, k, v = qkv

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 128, 128, True) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    g_fl = jax.grad(loss_fl)(q, k, v)
    assert jnp.allclose(g_ref, g_fl, atol=1e-4)


def test_ring_attention_matches_reference(qkv):
    q, k, v = qkv
    mesh = MeshSpec(dp=1, fsdp=2, sp=4, tp=1).build()
    ref = reference_attention(q, k, v, causal=True)
    ring = ring_attention_sharded(q, k, v, mesh, causal=True)
    assert jnp.allclose(ref, ring, atol=2e-5)


def test_ring_attention_sp8(qkv):
    q, k, v = qkv
    mesh = MeshSpec(dp=1, fsdp=1, sp=8, tp=1).build()
    ref = reference_attention(q, k, v, causal=True)
    ring = ring_attention_sharded(q, k, v, mesh, causal=True)
    assert jnp.allclose(ref, ring, atol=2e-5)


def test_flash_gradients_noncausal(qkv):
    q, k, v = qkv

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=False) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, False, None, 128, 128, True) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    g_fl = jax.grad(loss_fl)(q, k, v)
    assert jnp.allclose(g_ref, g_fl, atol=1e-4)


def test_flash_gradients_small_blocks(qkv):
    # exercises multi-block accumulation paths in dq and dkv kernels
    q, k, v = qkv

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 64, 64, True) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    g_fl = jax.grad(loss_fl)(q, k, v)
    assert jnp.allclose(g_ref, g_fl, atol=1e-4)
