"""Cloud-checkpoint storage: the pyarrow-fs layer under train/workflow.

Models the reference's StorageContext tests
(python/ray/train/tests/test_storage.py — mock:// filesystem) : local
paths and cloud URIs must behave identically, and a trainer must
fit -> crash -> resume entirely through a remote (mocked) filesystem.
"""

import os
import uuid

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)
from ray_tpu.train.storage import (StorageContext, delete_dir, download_dir,
                                   exists, get_fs_and_path, is_uri, join,
                                   register_filesystem, upload_dir)


def _mock_base() -> str:
    return f"mock://storage-test-{uuid.uuid4().hex[:8]}"


# ---------------------------------------------------------------- fs layer

def test_uri_detection_and_join():
    assert is_uri("gs://bucket/x") and is_uri("mock://y")
    assert not is_uri("/tmp/x") and not is_uri("relative/path")
    assert join("gs://b/base", "a", "b") == "gs://b/base/a/b"
    assert join("/tmp/base", "a") == os.path.join("/tmp/base", "a")


def test_local_fs_roundtrip(tmp_path):
    fs, path = get_fs_and_path(str(tmp_path))
    assert path == str(tmp_path)
    fs.create_dir(path + "/sub")
    assert os.path.isdir(tmp_path / "sub")


def test_mock_fs_upload_download_delete(tmp_path):
    src = tmp_path / "src"
    (src / "nested").mkdir(parents=True)
    (src / "a.txt").write_text("alpha")
    (src / "nested" / "b.bin").write_bytes(b"\x00" * 1024)

    dest = _mock_base() + "/ckpt"
    upload_dir(str(src), dest)
    assert exists(dest)

    back = tmp_path / "back"
    download_dir(dest, str(back))
    assert (back / "a.txt").read_text() == "alpha"
    assert (back / "nested" / "b.bin").read_bytes() == b"\x00" * 1024

    delete_dir(dest)
    assert not exists(dest)


def test_custom_scheme_registry(tmp_path):
    import fsspec
    from pyarrow.fs import FSSpecHandler, PyFileSystem
    mem = PyFileSystem(FSSpecHandler(fsspec.filesystem("memory")))
    register_filesystem("unittestfs", lambda: mem)
    fs, path = get_fs_and_path("unittestfs://abc/d")
    assert path == "abc/d"
    fs.create_dir("abc/d", recursive=True)


def test_storage_context_remote_persist_fetch(tmp_path):
    ctx = StorageContext(_mock_base(), experiment_name="exp1")
    assert ctx.is_remote
    local = tmp_path / "art"
    local.mkdir()
    (local / "f.txt").write_text("hello")
    dest = ctx.persist_dir(str(local), "run0")
    assert dest.endswith("exp1/run0")
    out = tmp_path / "fetched"
    ctx.fetch_dir(dest, str(out))
    assert (out / "f.txt").read_text() == "hello"


# ------------------------------------------------------------- checkpoint

def test_remote_checkpoint_handle(tmp_path):
    src = tmp_path / "ck"
    src.mkdir()
    (src / "w.txt").write_text("weights")
    uri = _mock_base() + "/ck"
    upload_dir(str(src), uri)

    ckpt = Checkpoint(uri)
    assert ckpt.is_remote
    local = ckpt.to_directory()
    assert open(os.path.join(local, "w.txt")).read() == "weights"
    # pack() must work on remote checkpoints (driver ships bytes to
    # workers, so workers never need fs credentials)
    packed = ckpt.pack()
    unpacked = packed.unpack_into(str(tmp_path / "un"))
    assert open(os.path.join(unpacked.path, "w.txt")).read() == "weights"


# ----------------------------------------------------- trainer end-to-end

def test_trainer_fit_kill_resume_via_mock_remote_fs(ray_start, tmp_path):
    """The verdict's bar: fit -> crash -> resume with checkpoints living
    on a (mocked) remote filesystem the whole time."""
    base = _mock_base()
    marker = str(tmp_path / "crashed_once")

    def loop(config):
        import os as _os
        import tempfile
        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            with open(_os.path.join(ckpt.as_directory(), "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 4):
            d = tempfile.mkdtemp()
            with open(_os.path.join(d, "step.txt"), "w") as f:
                f.write(str(step))
            train.report({"step": step},
                         checkpoint=Checkpoint.from_directory(d))
            if step == 1 and not _os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("simulated crash at step 1")

    trainer = JaxTrainer(
        loop, train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="remote-run", storage_path=base,
            checkpoint_config=CheckpointConfig(num_to_keep=2),
            failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    # the run resumed from the remote checkpoint (step 2 ran exactly
    # once after the crash, step 0 was not recomputed)
    steps = [m["step"] for m in result.metrics_dataframe]
    assert 2 in steps and steps.count(0) == 1, steps
    # final checkpoint is remote, retention applied remotely
    assert result.checkpoint is not None and result.checkpoint.is_remote
    local = result.checkpoint.to_directory()
    assert open(os.path.join(local, "step.txt")).read() == "3"
    fs, run_path = get_fs_and_path(join(base, "remote-run"))
    from pyarrow.fs import FileSelector
    names = [i.base_name for i in fs.get_file_info(FileSelector(run_path))
             if i.base_name.startswith("checkpoint_")]
    assert len(names) == 2, names


# ----------------------------------------------------- workflow on mock fs

def test_workflow_on_mock_storage(monkeypatch, ray_start):
    from ray_tpu import workflow

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", _mock_base())

    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(add.bind(1, 2), 10)
    assert workflow.run(dag, workflow_id="wf-mock") == 13
    assert workflow.get_status("wf-mock") == "SUCCESSFUL"
    assert workflow.get_output("wf-mock") == 13
    assert ("wf-mock", "SUCCESSFUL") in workflow.list_all()
    # resume is a no-op read from remote storage
    assert workflow.resume("wf-mock") == 13
    workflow.delete("wf-mock")
    assert workflow.get_status("wf-mock") == "NOT_FOUND"
