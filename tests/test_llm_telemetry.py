"""Request-lifecycle telemetry (ISSUE 5): SLO metrics, Prometheus
exposition, Chrome-trace lifecycles, the engine flight recorder, and
on-demand profiling.

The exactness gates pin the host-side recording to the engine's
actual lifecycle events: TTFT observations == finished requests, ITL
observations == generated tokens minus first tokens, finish-reason
counters exact, KV occupancy gauge == allocator.stats() at scrape.
Every engine here gets a UNIQUE Prometheus model tag so samples from
other tests sharing the process registry can never leak in.
"""

import json
import os
import re
import uuid
from pathlib import Path

import numpy as np
import jax.numpy as jnp
import pytest

from ray_tpu.models import llama
from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)

REPO = Path(__file__).resolve().parent.parent


def make_engine(**over):
    cfg = llama.config("debug", dtype=jnp.float32)
    kw = dict(model=cfg, max_batch_size=4, page_size=8, num_pages=64,
              prefill_buckets=(16, 32, 64),
              metrics_model_id=f"t{uuid.uuid4().hex[:10]}")
    kw.update(over)
    return InferenceEngine(EngineConfig(**kw))


def _sample(text: str, name: str, **tags):
    """Value of one exposition sample (exact tag match) or None."""
    for line in text.splitlines():
        if not line.startswith(name + "{") and line.split(" ")[0] != name:
            continue
        m = re.match(r"^([a-zA-Z0-9_]+)(?:\{(.*)\})? (.+)$", line)
        if m is None or m.group(1) != name:
            continue
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(2) or ""))
        if got == {k: str(v) for k, v in tags.items()}:
            return float(m.group(3))
    return None


# ----------------------------------------------------------- exposition

def test_metrics_exposition_exact_after_generation():
    """/metrics source of truth: TTFT observations == finished
    requests, ITL observations == generated tokens - first tokens,
    finish-reason counters exact, token counters exact."""
    eng = make_engine()
    tag = eng.config.metrics_model_id
    rng = np.random.default_rng(0)
    prompts = [rng.integers(2, 200, n).tolist() for n in (5, 9, 14)]
    reqs = eng.generate([list(p) for p in prompts],
                        SamplingParams(max_tokens=6))
    # one more request that stops on a token mid-stream
    stop = reqs[0].output_tokens[2]
    r = eng.generate([list(prompts[0])],
                     SamplingParams(max_tokens=30,
                                    stop_token_ids=(stop,)))[0]
    assert r.finish_reason == "stop"
    gen = sum(len(q.output_tokens) for q in reqs) + len(r.output_tokens)
    text = eng.prometheus_metrics()
    assert _sample(text, "ray_tpu_llm_ttft_seconds_count",
                   model=tag) == 4
    assert _sample(text, "ray_tpu_llm_itl_seconds_count",
                   model=tag) == gen - 4
    assert _sample(text, "ray_tpu_llm_queue_wait_seconds_count",
                   model=tag) == 4
    assert _sample(text, "ray_tpu_llm_e2e_latency_seconds_count",
                   model=tag) == 4
    assert _sample(text, "ray_tpu_llm_finished_total",
                   model=tag, reason="length") == 3.0
    assert _sample(text, "ray_tpu_llm_finished_total",
                   model=tag, reason="stop") == 1.0
    assert _sample(text, "ray_tpu_llm_generated_tokens_total",
                   model=tag) == gen
    assert _sample(text, "ray_tpu_llm_prompt_tokens_total",
                   model=tag) == sum(len(p) for p in prompts) \
        + len(prompts[0])
    # histogram sums are real latencies, not zeros
    assert _sample(text, "ray_tpu_llm_ttft_seconds_sum", model=tag) > 0
    # +Inf bucket equals the count (exposition well-formed)
    inf = None
    for line in text.splitlines():
        if line.startswith("ray_tpu_llm_ttft_seconds_bucket") \
                and f'model="{tag}"' in line and 'le="+Inf"' in line:
            inf = float(line.rsplit(" ", 1)[1])
    assert inf == 4


def test_kv_occupancy_gauge_matches_allocator_mid_flight():
    """Scrape-time gauges reflect LIVE engine state: occupancy and
    free-pages match allocator.stats() while requests hold pages,
    and running/waiting match the slot/queue state."""
    eng = make_engine(max_batch_size=2)
    tag = eng.config.metrics_model_id
    rng = np.random.default_rng(1)
    for i in range(3):           # 2 admit, 1 waits (2 slots)
        eng.add_request(Request(f"r{i}",
                                rng.integers(2, 200, 12).tolist(),
                                SamplingParams(max_tokens=16)))
    for _ in range(4):
        eng.step()
    text = eng.prometheus_metrics()
    st = eng.allocator.stats()
    assert _sample(text, "ray_tpu_llm_kv_pages_free",
                   model=tag) == st["free_pages"]
    assert _sample(text, "ray_tpu_llm_kv_pages_used",
                   model=tag) == st["used_pages"]
    assert _sample(text, "ray_tpu_llm_kv_page_occupancy",
                   model=tag) == pytest.approx(st["occupancy"])
    assert st["used_pages"] > 0          # requests really hold pages
    assert _sample(text, "ray_tpu_llm_running_requests",
                   model=tag) == 2
    assert _sample(text, "ray_tpu_llm_waiting_requests",
                   model=tag) == 1
    while eng.has_work():
        eng.step()
    text = eng.prometheus_metrics()
    assert _sample(text, "ray_tpu_llm_kv_pages_used", model=tag) == 0


def test_prefix_cache_hit_rate_gauge():
    eng = make_engine(max_batch_size=2, num_pages=96)
    tag = eng.config.metrics_model_id
    shared = np.random.default_rng(2).integers(2, 200, 24).tolist()
    eng.generate([shared + [5]], SamplingParams(max_tokens=2))
    eng.generate([shared + [9]], SamplingParams(max_tokens=2))
    text = eng.prometheus_metrics()
    rate = _sample(text, "ray_tpu_llm_prefix_cache_hit_rate",
                   model=tag)
    assert rate == pytest.approx(eng.allocator.cache_hit_rate)
    assert rate > 0              # second prompt hit the shared prefix


# ------------------------------------------------------------ chrome trace

def test_chrome_trace_well_formed_lifecycle():
    """GET /debug/trace payload: valid JSON, every request carries
    queued → prefill (with chunk marks) → first_token → decode →
    finished{reason} in causal order on its own tid."""
    eng = make_engine(max_prefill_tokens=8)   # forces chunked prefill
    rng = np.random.default_rng(3)
    reqs = eng.generate([rng.integers(2, 200, 20).tolist()],
                        SamplingParams(max_tokens=4))
    doc = json.loads(json.dumps(eng.chrome_trace()))   # JSON-able
    evs = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    for e in evs:
        assert {"ph", "name", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    by_name = {}
    rid = reqs[0].request_id
    for e in evs:
        if e.get("args", {}).get("request_id") == rid \
                or e["name"] == "prefill_chunk":
            by_name.setdefault(e["name"], []).append(e)
    assert set(by_name) >= {"queued", "prefill", "first_token",
                            "decode", "finished:length",
                            "prefill_chunk"}
    q, p = by_name["queued"][0], by_name["prefill"][0]
    d = by_name["decode"][0]
    assert q["ts"] <= p["ts"] <= d["ts"]
    assert p["args"]["prompt_tokens"] == 20
    assert d["args"]["generated_tokens"] == 4
    assert len(by_name["prefill_chunk"]) >= 2       # chunked at 8
    assert sum(e["args"]["tokens"]
               for e in by_name["prefill_chunk"]) == 20
    # every lifecycle event of one request shares one tid row
    tids = {e["tid"] for es in by_name.values() for e in es}
    assert len(tids) == 1


def test_chrome_trace_merges_tracing_ring():
    """The process tracing ring (RAY_TPU_TRACE spans) rides the same
    export — one viewer shows engine lifecycles AND live spans."""
    from ray_tpu.util import tracing

    eng = make_engine()
    tracing.clear()
    tracing.enable()
    try:
        with tracing.span("driver_side_work", "custom"):
            pass
    finally:
        tracing.disable()
    names = {e["name"] for e in eng.chrome_trace()["traceEvents"]}
    assert "driver_side_work" in names
    tracing.clear()


# --------------------------------------------------------- flight recorder

def test_flight_recorder_ring_and_structured_events():
    from ray_tpu.llm._internal.telemetry import FlightRecorder

    eng = make_engine(max_batch_size=2)
    rng = np.random.default_rng(4)
    eng.generate([rng.integers(2, 200, 8).tolist() for _ in range(2)],
                 SamplingParams(max_tokens=3))
    kinds = [e["event"] for e in eng.telemetry.recorder.events()]
    assert kinds.count("admission") == 2
    assert kinds.count("retirement") == 2
    assert "device_state_rebuild" in kinds
    evs = eng.telemetry.recorder.events()
    # events are seq-ordered with timestamps and structured fields
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    adm = next(e for e in evs if e["event"] == "admission")
    assert adm["prompt_tokens"] == 8 and "ts" in adm
    ret = next(e for e in evs if e["event"] == "retirement")
    assert ret["reason"] == "length" and ret["generated_tokens"] == 3

    # the ring is bounded: overflow drops oldest and counts drops
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("x", i=i)
    evs = rec.events()
    assert len(evs) == 4 and evs[0]["i"] == 6
    assert rec.stats() == {"events": 4, "total": 10, "dropped": 6}


def test_abort_paths_record_and_count():
    """Aborts from BOTH the waiting queue and a running slot land in
    the abort counter, the finish-reason counter, and the recorder."""
    eng = make_engine(max_batch_size=1, enable_prefix_caching=False)
    tag = eng.config.metrics_model_id
    rng = np.random.default_rng(5)
    r1 = Request("run1", rng.integers(2, 200, 6).tolist(),
                 SamplingParams(max_tokens=20))
    r2 = Request("wait1", rng.integers(2, 200, 6).tolist(),
                 SamplingParams(max_tokens=20))
    eng.add_request(r1)
    eng.add_request(r2)
    eng.step()
    assert eng.abort("wait1")            # still waiting (1 slot)
    assert eng.abort("run1")             # running
    text = eng.prometheus_metrics()
    assert _sample(text, "ray_tpu_llm_aborts_total", model=tag) == 2.0
    assert _sample(text, "ray_tpu_llm_finished_total",
                   model=tag, reason="abort") == 2.0
    evs = eng.telemetry.recorder.events()
    wheres = {e["request_id"]: e["where"] for e in evs
              if e["event"] == "abort"}
    assert wheres == {"wait1": "waiting", "run1": "running"}
    assert eng.telemetry.summary()["aborted"] == 2


# ----------------------------------------------------------- stats merge

def test_stats_requests_summary_and_budget_utilization():
    eng = make_engine()
    rng = np.random.default_rng(6)
    eng.generate([rng.integers(2, 200, 10).tolist() for _ in range(2)],
                 SamplingParams(max_tokens=5))
    s = eng.stats()["requests"]
    assert s["enabled"] is True
    assert s["finished"] == {"length": 2}
    assert s["generated_tokens"] == 10
    assert s["prompt_tokens"] == 20
    assert s["ttft_ms_avg"] > 0 and s["e2e_ms_avg"] >= s["ttft_ms_avg"]
    assert 0 < s["budget_utilization"] <= 1
    assert s["flight_recorder"]["events"] > 0
    assert s["live"] == 0


def test_telemetry_disabled_is_inert():
    """enable_metrics=False: generation works, stats say disabled,
    nothing lands in recorder or timelines (the bench overhead A/B's
    baseline arm)."""
    eng = make_engine(enable_metrics=False)
    rng = np.random.default_rng(7)
    reqs = eng.generate([rng.integers(2, 200, 8).tolist()],
                        SamplingParams(max_tokens=4))
    assert len(reqs[0].output_tokens) == 4
    assert eng.stats()["requests"] == {"enabled": False}
    assert eng.telemetry.recorder.events() == []
    # no request timelines (only the process tracing ring, if any)
    names = {e["name"] for e in eng.chrome_trace()["traceEvents"]}
    assert "queued" not in names


def test_disabled_and_enabled_engines_token_exact():
    """Instrumentation must never change what the engine computes:
    greedy output is bit-identical with metrics on and off."""
    rng = np.random.default_rng(8)
    prompts = [rng.integers(2, 200, n).tolist() for n in (6, 11)]

    def run(flag):
        eng = make_engine(enable_metrics=flag,
                          enable_prefix_caching=False)
        return [r.output_tokens for r in eng.generate(
            [list(p) for p in prompts], SamplingParams(max_tokens=8))]

    assert run(True) == run(False)


# ------------------------------------------------------------- profiling

def test_profile_next_ticks_writes_trace():
    eng = make_engine()
    rng = np.random.default_rng(9)
    d = eng.profile_next_ticks(2)
    with pytest.raises(RuntimeError, match="already"):
        eng.profile_next_ticks(1)        # one capture at a time
    eng.generate([rng.integers(2, 200, 8).tolist()],
                 SamplingParams(max_tokens=4))
    kinds = [e["event"] for e in eng.telemetry.recorder.events()]
    if "profile_error" in kinds:
        pytest.skip("jax.profiler unavailable on this backend")
    assert "profile_armed" in kinds and "profile_done" in kinds
    assert os.path.isdir(d) and os.listdir(d)     # trace files landed
    with pytest.raises(ValueError):
        eng.profile_next_ticks(0)
    # capture finished: re-arming is allowed again
    eng.profile_next_ticks(1, log_dir=d)
    eng.generate([rng.integers(2, 200, 8).tolist()],
                 SamplingParams(max_tokens=2))


def test_profile_disarms_on_mid_tick_exception(monkeypatch):
    """Regression (ISSUE 5 review): a tick that raises mid-capture
    must stop the jax.profiler trace and disarm — otherwise the
    capture records forever and every later profile_next_ticks()
    raises 'already armed' with no way out short of a restart."""
    eng = make_engine()
    rng = np.random.default_rng(3)
    eng.profile_next_ticks(4)

    def boom(touched):
        raise RuntimeError("mid-tick failure")

    monkeypatch.setattr(eng, "_step_tick", boom)
    with pytest.raises(RuntimeError, match="mid-tick failure"):
        eng.step()
    monkeypatch.undo()
    assert eng._profile is None           # disarmed, not wedged
    kinds = [e["event"] for e in eng.telemetry.recorder.events()]
    if "profile_error" not in kinds:      # backend supports profiling
        assert "profile_aborted" in kinds
    eng.profile_next_ticks(1)             # re-arming works again
    eng.generate([rng.integers(2, 200, 8).tolist()],
                 SamplingParams(max_tokens=2))


# ------------------------------------------------- instrumentation lint

def test_no_instrumentation_under_trace():
    """ISSUE 5 CI gate: no metrics/tracing/telemetry call site inside
    a traced function in the engine, the model forward, or the
    telemetry module itself — instrumentation stays on the host side
    of the dispatch boundary (jaxlint JL009)."""
    from tools.jaxlint.analyzer import analyze_paths

    findings = analyze_paths(
        [str(REPO / "ray_tpu/llm/_internal/engine.py"),
         str(REPO / "ray_tpu/llm/_internal/telemetry.py"),
         str(REPO / "ray_tpu/models/llama_infer.py")],
        root=str(REPO), select={"JL009"})
    assert findings == [], "\n".join(f.render() for f in findings)


# ----------------------------------------------------------- HTTP surface

@pytest.mark.usefixtures("ray_start")
def test_observability_http_endpoints(ray_start):
    """The router's ISSUE 5 surface over real HTTP: /metrics renders
    Prometheus text populated by a STREAMED generation, /debug/trace
    is a valid Chrome trace, /debug/events dumps the flight recorder,
    POST /debug/profile arms a capture — and an unknown GET is a
    clean 404, not the old 'invalid JSON body' 400."""
    import requests
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, build_openai_app

    app = build_openai_app({"llm_configs": [LLMConfig(
        model_id="m0", model_source="debug",
        engine_kwargs=dict(max_batch_size=4, page_size=8,
                           num_pages=128, prefill_buckets=(32, 64)))]})
    try:
        serve.run(app, name="llm", route_prefix="/",
                  http_options=serve.HTTPOptions(port=8129),
                  timeout_s=180)
        base = "http://127.0.0.1:8129"
        # the satellite fix first: unknown GET path → 404 JSON
        r = requests.get(f"{base}/not/a/route", timeout=30)
        assert r.status_code == 404
        assert "invalid JSON body" not in r.text
        assert "no route" in r.json()["error"]

        # streamed generation populates the SLO series
        r = requests.post(
            f"{base}/v1/chat/completions",
            json={"model": "m0", "max_tokens": 6, "stream": True,
                  "messages": [{"role": "user", "content": "hey"}]},
            stream=True, timeout=120)
        assert r.status_code == 200
        assert b"[DONE]" in b"".join(r.iter_content())

        r = requests.get(f"{base}/metrics", timeout=60)
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.text
        assert _sample(text, "ray_tpu_llm_ttft_seconds_count",
                       model="m0") >= 1
        assert _sample(text, "ray_tpu_llm_itl_seconds_count",
                       model="m0") >= 1
        assert _sample(text, "ray_tpu_llm_finished_total",
                       model="m0", reason="length") >= 1
        assert _sample(text, "ray_tpu_llm_kv_page_occupancy",
                       model="m0") is not None
        assert "# TYPE ray_tpu_llm_ttft_seconds histogram" in text
        # merged exposition: no duplicate series, one header per
        # family (in-process replicas share the registry — naive
        # concatenation would repeat every sample)
        samples = [l for l in text.splitlines()
                   if l and not l.startswith("#")]
        assert len(samples) == len(set(samples))
        types = [l for l in text.splitlines() if l.startswith("# TYPE ")]
        assert len(types) == len(set(types))

        r = requests.get(f"{base}/debug/trace", timeout=60)
        assert r.status_code == 200
        names = {e["name"] for e in r.json()["traceEvents"]}
        assert {"queued", "prefill", "decode"} <= names
        # ISSUE 7 satellite: ring fill/drop counters ride the doc
        ring = r.json()["metadata"]["m0"]["tracing_ring"]
        assert ring["capacity"] > 0 and "dropped" in ring

        r = requests.get(f"{base}/debug/events", timeout=60)
        kinds = {e["event"] for e in r.json()["models"]["m0"]}
        assert {"admission", "retirement"} <= kinds

        r = requests.post(f"{base}/debug/profile",
                          json={"ticks": 2}, timeout=60)
        assert r.status_code == 200
        m0 = r.json()["models"]["m0"]
        assert m0.get("error") or (m0["ticks"] == 2 and m0["log_dir"])

        # /stats carries the request SLO summary alongside tick_times
        r = requests.get(f"{base}/stats", timeout=60)
        reqs_summary = r.json()["models"]["m0"]["requests"]
        assert reqs_summary["finished"].get("length", 0) >= 1
    finally:
        serve.shutdown()


# ----------------------- perf families across fleet topologies (ISSUE 11)

def _drive(eng, n_req=2, gen=8):
    rng = np.random.default_rng(5)
    for i in range(n_req):
        eng.add_request(Request(
            f"pf{uuid.uuid4().hex[:6]}",
            rng.integers(2, 250, 12).tolist(),
            SamplingParams(max_tokens=gen)))
    while eng.has_work():
        eng.step()


def test_perf_families_shared_registry_topology():
    """In-process fleet replicas share ONE registry: engines tagged
    replica=r0/r1 drive work, a single render carries BOTH replicas'
    perf series (mfu/mbu gauges, flops and per-kind hbm_bytes
    counters, per-phase tokens_per_s), and merge_expositions over two
    sequential renders of the same registry dedups to one series per
    identity and one HELP/TYPE per family."""
    from ray_tpu.util.metrics import merge_expositions

    tag = f"pf{uuid.uuid4().hex[:10]}"
    engines = [make_engine(metrics_model_id=tag,
                           metrics_replica_id=f"r{i}")
               for i in range(2)]
    for eng in engines:
        _drive(eng)
    text = engines[0].prometheus_metrics()
    text = engines[1].prometheus_metrics()   # refreshes r1's gauges too
    for rid in ("r0", "r1"):
        assert _sample(text, "ray_tpu_llm_mfu",
                       model=tag, replica=rid) is not None
        assert _sample(text, "ray_tpu_llm_mbu",
                       model=tag, replica=rid) is not None
        v = _sample(text, "ray_tpu_llm_flops_total",
                    model=tag, replica=rid)
        assert v is not None and v > 0
        for kind in ("weights", "kv_read", "kv_write"):
            assert _sample(text, "ray_tpu_llm_hbm_bytes_total",
                           model=tag, replica=rid, kind=kind), kind
        for phase in ("decode", "prefill"):
            assert _sample(text, "ray_tpu_llm_tokens_per_s",
                           model=tag, replica=rid,
                           phase=phase) is not None
    merged = merge_expositions([text,
                                engines[0].prometheus_metrics()])
    assert merged.count("# TYPE ray_tpu_llm_mfu gauge") == 1
    assert merged.count("# TYPE ray_tpu_llm_hbm_bytes_total counter") \
        == 1
    series = [ln.rsplit(" ", 1)[0] for ln in merged.splitlines()
              if ln.startswith("ray_tpu_llm_mfu{")
              and f'model="{tag}"' in ln]
    assert len(series) == len(set(series)) == 2


def test_perf_families_cross_process_relabel_topology():
    """Separate-registry replicas render IDENTICAL series (no replica
    tag); the fleet scrape relabels each exposition with replica=<id>
    before merging — afterwards the new families must carry distinct
    per-replica series instead of colliding, with one header per
    family (the ISSUE 6 relabel contract extended to ISSUE 11)."""
    from ray_tpu.util.metrics import (merge_expositions,
                                      relabel_exposition)

    tag = f"px{uuid.uuid4().hex[:10]}"
    eng = make_engine(metrics_model_id=tag)     # replica unset -> ""
    _drive(eng)
    text = eng.prometheus_metrics()
    assert _sample(text, "ray_tpu_llm_mfu", model=tag) is not None
    merged = merge_expositions([
        relabel_exposition(text, {"replica": "rA"}),
        relabel_exposition(text, {"replica": "rB"}),
    ])
    for rid in ("rA", "rB"):
        assert _sample(merged, "ray_tpu_llm_mfu",
                       model=tag, replica=rid) is not None
        for kind in ("weights", "kv_read", "kv_write"):
            assert _sample(merged, "ray_tpu_llm_hbm_bytes_total",
                           model=tag, replica=rid, kind=kind), kind
        for phase in ("decode", "prefill"):
            assert _sample(merged, "ray_tpu_llm_tokens_per_s",
                           model=tag, replica=rid,
                           phase=phase) is not None
    # the un-relabeled series collided into per-replica identities:
    # nothing for this tag survives WITHOUT a replica label
    assert _sample(merged, "ray_tpu_llm_mfu", model=tag) is None
    assert merged.count("# TYPE ray_tpu_llm_tokens_per_s gauge") == 1


# --------- tenant + anomaly families across fleet topologies (ISSUE 13)

def _drive_tenants(eng, gen=8):
    """Two tenants: the default one ("" — label omitted) and an
    explicit one, so the tenant-labeled families carry both shapes."""
    rng = np.random.default_rng(5)
    for i in range(4):
        eng.add_request(Request(
            f"tn{uuid.uuid4().hex[:6]}",
            rng.integers(2, 250, 12).tolist(),
            SamplingParams(max_tokens=gen),
            tenant="acme" if i % 2 else ""))
    while eng.has_work():
        eng.step()


def test_tenant_anomaly_families_shared_registry_topology():
    """ISSUE 13 over the shared-registry fleet topology: both
    replicas' tenant counters and anomaly families render in one
    exposition; the default tenant's series carry NO tenant label
    (byte-identical single-tenant contract); merge_expositions over
    two renders dedups to one series per identity and one HELP/TYPE
    per family."""
    from ray_tpu.util.metrics import merge_expositions

    tag = f"tf{uuid.uuid4().hex[:10]}"
    engines = [make_engine(metrics_model_id=tag,
                           metrics_replica_id=f"r{i}")
               for i in range(2)]
    for eng in engines:
        _drive_tenants(eng)
    engines[0].prometheus_metrics()
    text = engines[1].prometheus_metrics()   # refreshes r1's gauges too
    for rid in ("r0", "r1"):
        # explicit tenant labeled; default tenant label-free
        for tenant_tags in ({"tenant": "acme"}, {}):
            v = _sample(text, "ray_tpu_llm_tenant_flops_total",
                        model=tag, replica=rid, **tenant_tags)
            assert v is not None and v > 0, (rid, tenant_tags)
            assert _sample(text, "ray_tpu_llm_tenant_hbm_bytes_total",
                           model=tag, replica=rid,
                           **tenant_tags) is not None
            for phase in ("decode", "prefill"):
                assert _sample(text, "ray_tpu_llm_tenant_tokens_total",
                               model=tag, replica=rid, phase=phase,
                               **tenant_tags) is not None
        assert _sample(text, "ray_tpu_llm_tick_anomaly_rate",
                       model=tag, replica=rid) == 0.0
    merged = merge_expositions([text,
                                engines[0].prometheus_metrics()])
    assert merged.count(
        "# TYPE ray_tpu_llm_tenant_flops_total counter") == 1
    assert merged.count(
        "# TYPE ray_tpu_llm_tick_anomaly_rate gauge") == 1
    series = [ln.rsplit(" ", 1)[0] for ln in merged.splitlines()
              if ln.startswith("ray_tpu_llm_tenant_flops_total{")
              and f'model="{tag}"' in ln]
    # 2 replicas x 2 tenants, each exactly once after the merge
    assert len(series) == len(set(series)) == 4


def test_tenant_anomaly_families_cross_process_relabel_topology():
    """ISSUE 13 over the separate-registry topology: identical
    expositions relabel with replica=<id> before merging — tenant and
    anomaly series split per replica instead of colliding, and the
    tenant label survives the relabel untouched."""
    from ray_tpu.util.metrics import (merge_expositions,
                                      relabel_exposition)

    tag = f"tx{uuid.uuid4().hex[:10]}"
    eng = make_engine(metrics_model_id=tag)     # replica unset -> ""
    _drive_tenants(eng)
    text = eng.prometheus_metrics()
    assert _sample(text, "ray_tpu_llm_tenant_flops_total",
                   model=tag, tenant="acme") is not None
    merged = merge_expositions([
        relabel_exposition(text, {"replica": "rA"}),
        relabel_exposition(text, {"replica": "rB"}),
    ])
    for rid in ("rA", "rB"):
        for tenant_tags in ({"tenant": "acme"}, {}):
            assert _sample(merged, "ray_tpu_llm_tenant_flops_total",
                           model=tag, replica=rid,
                           **tenant_tags) is not None, (rid,
                                                        tenant_tags)
        assert _sample(merged, "ray_tpu_llm_tick_anomaly_rate",
                       model=tag, replica=rid) is not None
        assert _sample(merged, "ray_tpu_llm_tick_anomalies_total",
                       model=tag, replica=rid) is None  # none fired
    # nothing for this tag survives WITHOUT a replica label
    assert _sample(merged, "ray_tpu_llm_tenant_flops_total",
                   model=tag, tenant="acme") is None
    assert merged.count(
        "# TYPE ray_tpu_llm_tenant_tokens_total counter") == 1
