"""Daemon-local ACTOR creation (distributed dispatch, VERDICT r4
missing #1 / next-round #2): the daemon grants actor-creation leases
from its controller-delegated block, the controller's directory learns
about the actor AFTER the fact via an actor_started report that carries
the creation spec — reference parity: the GCS actor scheduler leases
workers through raylets (gcs_actor_scheduler.h) rather than placing
every actor through the central scheduler."""

import time

import pytest

import ray_tpu


@pytest.fixture()
def fresh_cluster():
    # Force-enable: default "auto" disables local grants when the
    # controller shares the daemon's host (this box).
    from ray_tpu._private.config import get_config
    cfg = get_config()
    prev = cfg.local_lease_enabled
    cfg.local_lease_enabled = "1"
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()
    cfg.local_lease_enabled = prev


@ray_tpu.remote
class Echo:
    def __init__(self, tag="t"):
        self.tag = tag
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n

    def whoami(self):
        import os
        return os.getpid(), self.tag


def test_local_actor_created_and_callable(fresh_cluster):
    rt = fresh_cluster
    daemon = rt.head_daemon
    a = Echo.options(num_cpus=0).remote("local")
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    assert ray_tpu.get(a.bump.remote(), timeout=30) == 2
    # the grant happened on the daemon, without controller scheduling
    assert daemon._local_actor_slots, \
        "actor creation did not take the daemon-local path"
    # controller directory converges (async registration)
    deadline = time.time() + 20
    while time.time() < deadline and not rt.controller.actors:
        time.sleep(0.1)
    assert rt.controller.actors, "controller never learned the actor"
    entry = list(rt.controller.actors.values())[0]
    assert entry.state == "ALIVE"


def test_local_actor_slot_returned_on_kill(fresh_cluster):
    rt = fresh_cluster
    daemon = rt.head_daemon
    a = Echo.options(num_cpus=1).remote()
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    assert list(daemon._local_actor_slots.values()) == [(("CPU", 1.0),)]
    # wait until the controller knows it (kill routes through the
    # directory)
    deadline = time.time() + 20
    while time.time() < deadline and not rt.controller.actors:
        time.sleep(0.1)
    ray_tpu.kill(a)
    deadline = time.time() + 30
    while time.time() < deadline and daemon._local_actor_slots:
        time.sleep(0.2)
    assert not daemon._local_actor_slots, \
        "slot not credited back on actor death"


def test_named_actor_takes_scheduled_path(fresh_cluster):
    rt = fresh_cluster
    daemon = rt.head_daemon
    a = Echo.options(name="named-one", num_cpus=0).remote()
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    assert not daemon._local_actor_slots
    got = ray_tpu.get_actor("named-one")
    assert ray_tpu.get(got.bump.remote(), timeout=30) == 2


def test_local_actor_init_failure_surfaces(fresh_cluster):
    rt = fresh_cluster
    daemon = rt.head_daemon

    @ray_tpu.remote
    class Boom:
        def __init__(self):
            raise RuntimeError("no thanks")

        def hi(self):
            return 1

    b = Boom.options(num_cpus=0).remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.hi.remote(), timeout=60)
    assert not daemon._local_actor_slots, "failed creation leaked a slot"


def test_local_actor_restarts_via_controller(fresh_cluster):
    """The async spec registration must be enough for the controller to
    RESTART a locally-created actor after its worker dies."""
    import os
    import signal
    rt = fresh_cluster
    a = Echo.options(num_cpus=0, max_restarts=1).remote("r")
    pid, _ = ray_tpu.get(a.whoami.remote(), timeout=60)
    # wait for directory registration before killing
    deadline = time.time() + 20
    while time.time() < deadline and not rt.controller.actors:
        time.sleep(0.1)
    os.kill(pid, signal.SIGKILL)
    deadline = time.time() + 60
    new_pid = None
    while time.time() < deadline:
        try:
            new_pid, _ = ray_tpu.get(a.whoami.remote(), timeout=10)
            if new_pid != pid:
                break
        except Exception:
            time.sleep(0.3)
    assert new_pid is not None and new_pid != pid, \
        "actor did not restart on a fresh worker"


def test_controller_restart_reconciles_actor_slots(fresh_cluster):
    """Controller-restart reconciliation covers slots held by local
    ACTORS: either re-acquired (death later credits the block) or shed
    (death credits nothing) — never double-booked."""
    rt = fresh_cluster
    daemon = rt.head_daemon
    loop = rt.loop_runner
    a = Echo.options(num_cpus=1).remote()
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    assert daemon._local_actor_slots
    deadline = time.time() + 20
    while time.time() < deadline and not rt.controller.actors:
        time.sleep(0.1)

    async def _wipe_and_reconcile():
        ctrl = rt.controller
        node = ctrl.nodes[daemon.node_id]
        free = sum(daemon._lease_blocks.values())
        for _ in range(free + 1):        # +1: the live actor slot
            node.release({"CPU": 1.0})
        ctrl.delegations.clear()
        await daemon._reconcile_delegations()

    loop.run_sync(_wipe_and_reconcile(), timeout=30)
    ctrl = rt.controller
    node = ctrl.nodes[daemon.node_id]
    acquired = (node.resources_total["CPU"]
                - node.resources_avail["CPU"])
    backing = (sum(daemon._lease_blocks.values())
               + sum(1 for aid in daemon._local_actor_slots
                     if aid not in daemon._unbacked_actor_slots)
               + len(daemon._local_leases))
    assert abs(acquired - backing) < 1e-6, (acquired, backing)
    # actor still alive and callable after reconciliation
    assert ray_tpu.get(a.bump.remote(), timeout=30) == 2
    ray_tpu.kill(a)
    deadline = time.time() + 30
    while time.time() < deadline and daemon._local_actor_slots:
        time.sleep(0.2)
    assert not daemon._local_actor_slots

# ------------------------------------------------------- TPU local leases

@pytest.fixture()
def tpu_cluster():
    from ray_tpu._private.config import get_config
    cfg = get_config()
    prev = cfg.local_lease_enabled
    cfg.local_lease_enabled = "1"
    rt = ray_tpu.init(num_cpus=4, num_tpus=2)
    yield rt
    ray_tpu.shutdown()
    cfg.local_lease_enabled = prev


def test_tpu_tasks_via_local_lease(tpu_cluster):
    """TPU tasks ride daemon-local leases: chips pinned per lease,
    TPU_VISIBLE_CHIPS isolation applied, chips freed when leases die."""
    rt = tpu_cluster
    daemon = rt.head_daemon

    @ray_tpu.remote(num_tpus=1)
    def which_chips():
        import os
        return os.environ.get("TPU_VISIBLE_CHIPS")

    got = ray_tpu.get([which_chips.remote() for _ in range(8)],
                      timeout=120)
    assert all(g is not None for g in got), got
    assert daemon.local_leases_granted > 0, \
        "TPU storm never used the local-grant path"
    # leases idle out -> all chips return
    deadline = time.time() + 30
    while time.time() < deadline and len(daemon._free_tpu_chips) < 2:
        time.sleep(0.25)
    assert sorted(daemon._free_tpu_chips) == [0, 1]


def test_tpu_actor_via_local_creation(tpu_cluster):
    rt = tpu_cluster
    daemon = rt.head_daemon

    @ray_tpu.remote(num_tpus=1, num_cpus=0)
    class Chip:
        def visible(self):
            import os
            return os.environ.get("TPU_VISIBLE_CHIPS")

    a = Chip.remote()
    vis = ray_tpu.get(a.visible.remote(), timeout=60)
    assert vis is not None
    assert daemon._local_actor_slots, "actor skipped the local path"
    assert len(daemon._free_tpu_chips) == 1   # one chip held by actor
    deadline = time.time() + 20
    while time.time() < deadline and not rt.controller.actors:
        time.sleep(0.1)
    ray_tpu.kill(a)
    deadline = time.time() + 30
    while time.time() < deadline and len(daemon._free_tpu_chips) < 2:
        time.sleep(0.2)
    assert sorted(daemon._free_tpu_chips) == [0, 1], \
        "actor death did not free its chip"
