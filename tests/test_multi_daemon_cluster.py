"""Multi-daemon cluster: scheduling, gossip, transfer, and the n:n actor
storm across real daemon PROCESSES (VERDICT r3 #3; reference parity:
python/ray/cluster_utils.py:135 driving python/ray/tests distributed
suites)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_cpus=2)
    # 3 extra daemon processes -> 4 nodes total on this box
    for _ in range(3):
        c.add_node(num_cpus=2)
    c.wait_for_nodes(4)
    yield c
    c.shutdown()


def test_tasks_spread_across_daemon_processes(cluster):
    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    nodes = set(ray_tpu.get([where.remote() for _ in range(24)]))
    assert len(nodes) >= 3, f"tasks landed on only {len(nodes)} nodes"


def test_cross_node_object_transfer(cluster):
    """Objects produced on one daemon process are fetched by workers on
    another (chunked transfer over real sockets)."""
    import numpy as np

    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def produce(tag):
        return np.full((1 << 20,), tag, np.uint8)   # 1 MiB

    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def consume(arr):
        return int(arr[0]), ray_tpu.get_runtime_context().get_node_id()

    refs = [produce.remote(i) for i in range(8)]
    out = ray_tpu.get([consume.remote(r) for r in refs])
    assert [t for t, _ in out] == list(range(8))
    assert len({n for _, n in out}) >= 2


def test_actors_spread_and_call_across_nodes(cluster):
    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    class Echo:
        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

        def add(self, x):
            return x + 1

    actors = [Echo.remote() for _ in range(6)]
    nodes = set(ray_tpu.get([a.where.remote() for a in actors]))
    assert len(nodes) >= 3
    assert ray_tpu.get([a.add.remote(i) for i, a in
                        enumerate(actors)]) == [1, 2, 3, 4, 5, 6]


def test_node_kill_detected_and_tasks_recover(cluster):
    """SIGKILL a daemon process: the controller's health probes must
    declare it dead and retriable tasks must re-run elsewhere."""
    victim = cluster.add_node(num_cpus=1, resources={"victim": 1.0})

    @ray_tpu.remote(num_cpus=0, resources={"victim": 0.5}, max_retries=2)
    def slow():
        time.sleep(5)
        return ray_tpu.get_runtime_context().get_node_id()

    ref = slow.remote()
    time.sleep(1.0)               # let it start on the victim
    cluster.remove_node(victim)   # SIGKILL, wait for dead

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def anywhere():
        return "ok"

    # cluster still schedules; the victim-pinned task can never rerun
    # (its resource is gone) but must not wedge the rest of the cluster
    assert ray_tpu.get([anywhere.remote() for _ in range(8)]) == ["ok"] * 8


def test_gossip_converges_at_four_nodes(cluster):
    """Every node's resource view reaches the controller: totals
    reported by the state API cover all alive nodes."""
    from ray_tpu.util.state import list_nodes
    nodes = [n for n in list_nodes() if n["alive"]]
    assert len(nodes) >= 4
    total_cpu = sum(n["resources_total"].get("CPU", 0) for n in nodes)
    assert total_cpu >= 7.0       # 2 head + 3x2 workers (minus victim)
