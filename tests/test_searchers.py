"""BOHB searcher + external adapter plumbing.

Reference parity: tune/search/bohb (TuneBOHB + HyperBandForBOHB) and
the optuna/hyperopt adapters.
"""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import BOHBSearch, HyperBandScheduler


def test_bohb_optimizes_with_hyperband(ray_start):
    """BOHB + HyperBand finds the bowl minimum; late suggestions
    concentrate near it (model phase engaged)."""

    def objective(config):
        x, y = config["x"], config["y"]
        base = (x - 0.3) ** 2 + (y + 0.5) ** 2
        # converging trials: deeper budgets give cleaner estimates
        for it in range(4):
            tune.report({"loss": base * (1.0 + 1.0 / (it + 1))})

    space = {"x": tune.uniform(-2.0, 2.0), "y": tune.uniform(-2.0, 2.0)}
    bohb = BOHBSearch(space, metric="loss", mode="min", num_samples=40,
                      n_startup_trials=8, seed=0)
    hb = HyperBandScheduler(metric="loss", mode="min", max_t=4)
    result = tune.run(objective, config=space, search_alg=bohb,
                      scheduler=hb, metric="loss", mode="min", verbose=0)
    best = result.get_best_result().metrics["loss"]
    assert best < 0.6, best
    # per-budget pools were actually built (the BOHB-vs-TPE difference)
    assert any(len(p) >= bohb.min_points
               for p in bohb._budget_scores.values())


def test_bohb_prefers_deepest_budget_model():
    space = {"x": tune.uniform(0.0, 1.0)}
    bohb = BOHBSearch(space, metric="m", mode="max", n_startup_trials=2,
                      min_points_in_model=2, seed=1)
    # three configs observed at budget 1, two survivors at budget 3
    for tid, xv, m1 in [("a", 0.1, 1.0), ("b", 0.5, 2.0), ("c", 0.9, 3.0)]:
        bohb.suggest(tid)
        bohb._trials[tid]["x"] = xv        # pin for determinism
        bohb.on_trial_result(tid, {"m": m1, "training_iteration": 1})
    for tid, m3 in [("b", 5.0), ("c", 4.0)]:
        bohb.on_trial_result(tid, {"m": m3, "training_iteration": 3})
    good, _bad = bohb._split()
    # the budget-3 pool (b best with 5.0) must drive the split, not the
    # budget-1 ranking (where c led with 3.0)
    assert good[0][0]["x"] == 0.5


def test_adapter_space_translation():
    from ray_tpu.tune.search.adapters import domain_spec, split_space

    space = {
        "lr": tune.loguniform(1e-5, 1e-1),
        "dim": tune.randint(8, 64),
        "act": tune.choice(["relu", "gelu"]),
        "fixed": 7,
    }
    domains, fixed = split_space(space)
    assert domains["lr"] == ("float", 1e-5, 1e-1, True, None)
    assert domains["dim"][0] == "int" and domains["dim"][1:3] == (8, 64)
    assert domains["act"] == ("cat", ["relu", "gelu"])
    assert fixed == {"fixed": 7}

    with pytest.raises(ValueError, match="grid_search"):
        split_space({"g": tune.grid_search([1, 2])})


def test_adapters_require_their_libraries():
    """Without optuna/hyperopt installed the adapters raise ImportError
    pointing at the native equivalents (reference behavior)."""
    space = {"x": tune.uniform(0, 1)}
    for cls_name in ("OptunaSearch", "HyperOptSearch"):
        cls = getattr(tune, cls_name)
        try:
            searcher = cls(space, metric="m", mode="max")
        except ImportError as e:
            assert "TPESearch" in str(e)
        else:
            # library present: the adapter must actually suggest
            cfg = searcher.suggest("t1")
            assert 0.0 <= cfg["x"] <= 1.0
