"""Interleaved 1F1B pipeline (VERDICT r4 weak #6 / next-round #5).

Gates: (1) the schedule builder emits valid dependency-respecting
tables and the interleaved async bubble beats GPipe at pp=4;
(2) hand-scheduled loss AND grads match the dense single-device
autodiff path; (3) the 1f1b train step runs end-to-end on the pp=4
virtual mesh and learns."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import llama, pipeline_1f1b
from ray_tpu.models.pipeline_1f1b import (build_schedule,
                                          gpipe_bubble_fraction)
from ray_tpu.parallel import MeshSpec


def _check_valid(s):
    """Every op exactly once; F/B dependency order respected."""
    svc = s.n_chunks * s.pp
    f_at = np.full((s.n_micro, svc), -1)
    b_at = np.full((s.n_micro, svc), -1)
    for t in range(s.ticks):
        for d in range(s.pp):
            if s.f_valid[t, d]:
                m, c = int(s.f_mb[t, d]), int(s.f_chunk[t, d])
                sv = c * s.pp + d
                assert f_at[m, sv] == -1
                f_at[m, sv] = t
            if s.b_valid[t, d]:
                m, c = int(s.b_mb[t, d]), int(s.b_chunk[t, d])
                sv = c * s.pp + d
                assert b_at[m, sv] == -1
                b_at[m, sv] = t
    assert (f_at >= 0).all() and (b_at >= 0).all()
    for m in range(s.n_micro):
        for sv in range(1, svc):
            assert f_at[m, sv] > f_at[m, sv - 1]
        assert b_at[m, svc - 1] > f_at[m, svc - 1]
        for sv in range(svc - 1):
            assert b_at[m, sv] > b_at[m, sv + 1]


@pytest.mark.parametrize("m,pp,v", [(8, 4, 1), (8, 4, 2), (16, 4, 2),
                                    (8, 2, 2), (5, 4, 1)])
def test_schedule_valid(m, pp, v):
    _check_valid(build_schedule(m, pp, v))


def test_interleaved_bubble_beats_gpipe_at_pp4():
    """The r4-verdict gate: measured bubble (async dependency timing,
    F=1/B=2 cost) < GPipe's at pp=4."""
    for m in (8, 16):
        s = build_schedule(m, 4, 2)
        assert s.async_bubble_fraction() < gpipe_bubble_fraction(m, 4), m
    # interleaving deeper shrinks it further
    assert (build_schedule(8, 4, 4).async_bubble_fraction()
            < build_schedule(8, 4, 2).async_bubble_fraction())


def _dense_loss_and_grads(cfg, params, tokens):
    (loss, metrics), grads = jax.value_and_grad(
        lambda p: llama.loss_fn(cfg, p, tokens, None), has_aux=True)(params)
    return loss, grads


@pytest.mark.parametrize("v", [1, 2])
def test_1f1b_grads_match_dense(v):
    cfg = llama.config(
        "debug", dtype=jnp.float32, n_layers=2 * v, pp_microbatches=8,
        pp_schedule="1f1b", pp_interleave=v, remat=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(2, cfg.vocab_size, (8, 32)), jnp.int32)

    dense_loss, dense_grads = _dense_loss_and_grads(cfg, params, tokens)

    mesh = MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=2).build(jax.devices()[:2])
    with jax.set_mesh(mesh):
        loss, metrics, grads = jax.jit(
            lambda p, t: pipeline_1f1b.loss_and_grads(cfg, p, t, mesh)
        )(params, tokens)

    np.testing.assert_allclose(float(loss), float(dense_loss),
                               rtol=1e-5, atol=1e-6)
    flat_d, tree_d = jax.tree.flatten(dense_grads)
    flat_p, tree_p = jax.tree.flatten(grads)
    assert tree_d == tree_p
    for gd, gp in zip(flat_d, flat_p):
        np.testing.assert_allclose(
            np.asarray(gp, np.float32), np.asarray(gd, np.float32),
            rtol=2e-4, atol=2e-5)


def test_1f1b_trains_on_pp4_mesh():
    from ray_tpu.models.training import TrainStepBundle
    cfg = llama.config(
        "debug", dtype=jnp.float32, n_layers=8, pp_microbatches=8,
        pp_schedule="1f1b", pp_interleave=2, remat=False)
    mesh = MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4).build(jax.devices()[:8])
    bundle = TrainStepBundle(cfg, mesh)
    state = bundle.init_state(0)
    rng = np.random.default_rng(0)
    tokens = bundle.shard_batch(jnp.asarray(
        rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32))
    losses = []
    for _ in range(4):
        state, metrics = bundle.step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
