"""Quantized KV serving end-to-end (ISSUE 16).

Gates, layer by layer:
- `ops/kv_quant.py` quantize/dequantize row properties: per-(row,
  head) scales, bounded relative error, exact zeros, byte accounting;
- the quantized ragged Pallas kernel (interpret mode — the same
  program compiles on TPU) matches the f32 dense oracle FED THE SAME
  DEQUANTIZED VALUES across GQA widths, partial last pages,
  decode-only batches, padding rows, per-page scale extremes, and
  block-size choices — the fused dequant must be exact, quantization
  error lives only in the (tested) quantizer;
- quantize-at-append (`scatter_kv_quant`) writes only its target rows
  and round-trips through `gather_kv_quant` within the quantizer's
  error bound;
- engine-level: int8/fp8 preempt/restore and session migration are
  token-exact vs a same-kind oracle (quantization changes tokens;
  moving pages must not), kind-mismatched imports are REJECTED, and
  the byte gauges report the configured page dtype;
- wire v2: scale arrays + kv_dtype round-trip byte-exact, v1 frames
  still decode as f32, corruption anywhere in the scale region raises
  the transport-error family, self-inconsistent quant frames are bad
  payloads;
- EQuARX-style quantized collectives match the f32 lax collectives
  within per-kind tolerance on a multi-device CPU mesh.
"""

import dataclasses
import functools
import zlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)
from ray_tpu.models import llama
from ray_tpu.ops import kv_quant
from ray_tpu.ops import quantized_collectives as qcoll
from ray_tpu.ops.paged_attention import gather_kv_quant, scatter_kv_quant
from ray_tpu.ops.ragged_paged_attention import (
    ragged_attention_dense_oracle, ragged_paged_attention_pallas)
from ray_tpu.serve.llm import kv_transport as kvt

QUANT_KINDS = ("int8", "fp8")
# quantizer round-trip bounds: int8 has 7 value bits per row-scaled
# lane; fp8 e4m3 carries ~3 mantissa bits
RT_RTOL = {"int8": 0.01, "fp8": 0.07}


# ------------------------------------------------------- kv_quant unit

@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_quantize_rows_roundtrip_bounded(kind):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 7, 3, 16)).astype(np.float32)
                    * 4.0)
    q, s = kv_quant.quantize_rows(x, kind)
    assert q.dtype == kv_quant.storage_dtype(kind)
    assert s.shape == x.shape[:-1] and s.dtype == jnp.float32
    y = kv_quant.dequantize_rows(q, s, kind)
    rel = float(jnp.linalg.norm(y - x) / jnp.linalg.norm(x))
    assert rel < RT_RTOL[kind], (kind, rel)


@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_quantize_rows_zero_rows_exact_and_no_nan(kind):
    x = jnp.zeros((3, 4, 2, 8), jnp.float32)
    q, s = kv_quant.quantize_rows(x, kind)
    y = kv_quant.dequantize_rows(q, s, kind)
    assert float(jnp.max(jnp.abs(y))) == 0.0
    assert not bool(jnp.any(jnp.isnan(y)))


@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_quantize_rows_scale_extremes(kind):
    """Rows spanning 8 orders of magnitude: per-row scales keep the
    RELATIVE error flat across the range (one global scale would
    crush the small rows to zero)."""
    rng = np.random.default_rng(1)
    base = rng.normal(size=(8, 1, 1, 16)).astype(np.float32)
    mags = (10.0 ** np.arange(-4, 4)).reshape(8, 1, 1, 1)
    x = jnp.asarray(base * mags)
    y = kv_quant.dequantize_rows(*kv_quant.quantize_rows(x, kind),
                                 kind=kind)
    for i in range(8):
        num = float(jnp.linalg.norm(y[i] - x[i]))
        den = float(jnp.linalg.norm(x[i]))
        assert num / den < RT_RTOL[kind], (kind, i, num / den)


def test_kv_quant_kind_table_and_bytes():
    assert kv_quant.validate_kind("f32") == "f32"
    with pytest.raises(ValueError):
        kv_quant.validate_kind("int4")
    with pytest.raises(ValueError):
        kv_quant.quantize_rows(jnp.zeros((2, 4)), "f32")
    # token_row_bytes: f32 rows are 4 B/value; quant rows are 1
    # B/value + one 4 B scale per head
    assert kv_quant.token_row_bytes("f32", 2, 32) == 2 * 32 * 4
    for kind in QUANT_KINDS:
        assert kv_quant.token_row_bytes(kind, 2, 32) == 2 * 32 + 2 * 4
    # >= 1.9x footprint/read-bytes (the perf_opt headline) at every
    # realistic head_dim
    for d in (32, 64, 128, 256):
        assert (kv_quant.token_row_bytes("f32", 1, d)
                / kv_quant.token_row_bytes("int8", 1, d)) >= 1.9


# ---------------------------------------- quantized kernel vs oracle

def _quant_case(rng, segs, kind, page_size=4, kvh=2, group=2, d=8,
                pad=0, mags=None):
    """A ragged batch whose PAGED context is quantized storage. The
    oracle sees the DEQUANTIZED values (quantize_rows is per-(token,
    head) on both layouts, so quantizing the dense context gives
    byte-identical values to quantizing the pages) — any kernel/
    oracle gap is a fused-dequant bug, not quantization error."""
    b = len(segs)
    h = kvh * group
    max_ctx = max((s for s, _ in segs), default=0)
    max_pages = max(-(-max(s + n for s, n in segs) // page_size), 1)
    num_pages = b * max_pages + 1
    dense_k = rng.normal(size=(b, max(max_ctx, 1), kvh, d)).astype(
        np.float32)
    dense_v = rng.normal(size=(b, max(max_ctx, 1), kvh, d)).astype(
        np.float32)
    if mags is not None:                  # per-position magnitude ramp
        dense_k = dense_k * mags
        dense_v = dense_v * mags
    kq, ks_d = kv_quant.quantize_rows(jnp.asarray(dense_k), kind)
    vq, vs_d = kv_quant.quantize_rows(jnp.asarray(dense_v), kind)
    dense_k_dq = np.asarray(kv_quant.dequantize_rows(kq, ks_d, kind))
    dense_v_dq = np.asarray(kv_quant.dequantize_rows(vq, vs_d, kind))
    k_pages = np.zeros((num_pages, page_size, kvh, d),
                       np.asarray(kq).dtype)
    v_pages = np.zeros_like(k_pages)
    k_scales = np.zeros((num_pages, page_size, kvh), np.float32)
    v_scales = np.zeros_like(k_scales)
    tables = np.arange(b * max_pages, dtype=np.int32).reshape(
        b, max_pages)
    for s in range(b):
        for p in range(segs[s][0]):
            page, row = tables[s, p // page_size], p % page_size
            k_pages[page, row] = np.asarray(kq)[s, p]
            v_pages[page, row] = np.asarray(vq)[s, p]
            k_scales[page, row] = np.asarray(ks_d)[s, p]
            v_scales[page, row] = np.asarray(vs_d)[s, p]
    t = sum(n for _, n in segs) + pad
    slot_ids = np.zeros(t, np.int32)
    positions = np.zeros(t, np.int32)
    valid = np.zeros(t, bool)
    cur = 0
    for s, (start, n) in enumerate(segs):
        slot_ids[cur:cur + n] = s
        positions[cur:cur + n] = np.arange(start, start + n)
        valid[cur:cur + n] = True
        cur += n
    q = rng.normal(size=(t, h, d)).astype(np.float32)
    k_new = rng.normal(size=(t, kvh, d)).astype(np.float32)
    v_new = rng.normal(size=(t, kvh, d)).astype(np.float32)
    start = np.asarray([s for s, _ in segs], np.int32)
    return dict(q=q, k_pages=k_pages, v_pages=v_pages,
                k_scales=k_scales, v_scales=v_scales, tables=tables,
                slot_ids=slot_ids, positions=positions, valid=valid,
                start=start, k_new=k_new, v_new=v_new,
                dense_k=dense_k_dq, dense_v=dense_v_dq)


def _quant_kernel_out(c, **kw):
    kw.setdefault("q_block", 4)
    kw.setdefault("pages_per_block", 2)
    return np.asarray(ragged_paged_attention_pallas(
        jnp.asarray(c["q"]), jnp.asarray(c["k_pages"]),
        jnp.asarray(c["v_pages"]), jnp.asarray(c["tables"]),
        jnp.asarray(c["slot_ids"]), jnp.asarray(c["positions"]),
        jnp.asarray(c["valid"]), jnp.asarray(c["start"]),
        jnp.asarray(c["k_new"]), jnp.asarray(c["v_new"]),
        k_scales=jnp.asarray(c["k_scales"]),
        v_scales=jnp.asarray(c["v_scales"]), **kw))


def _oracle_out(c):
    return ragged_attention_dense_oracle(
        c["q"], c["dense_k"], c["dense_v"], c["k_new"], c["v_new"],
        c["slot_ids"], c["positions"], c["valid"], c["start"])


@pytest.mark.parametrize("kind", QUANT_KINDS)
@pytest.mark.parametrize("name,segs,pad,kvh,group", [
    ("decode_only", [(5, 1), (11, 1), (3, 1), (8, 1)], 0, 2, 2),
    ("mixed", [(7, 1), (0, 5), (12, 1), (4, 6)], 0, 2, 2),
    ("gqa_group1", [(6, 2), (0, 3), (10, 1)], 0, 3, 1),
    ("gqa_group4", [(6, 2), (0, 3), (10, 1)], 0, 2, 4),
    ("partial_last_page", [(5, 3), (9, 1), (1, 2), (6, 1)], 0, 2, 2),
    ("padding_rows", [(5, 1), (0, 4)], 7, 2, 2),
])
def test_quant_kernel_matches_dequant_oracle(name, segs, pad, kvh,
                                             group, kind):
    rng = np.random.default_rng(zlib.crc32(f"{name}/{kind}".encode()))
    c = _quant_case(rng, segs, kind, pad=pad, kvh=kvh, group=group)
    out = _quant_kernel_out(c, interpret=True)
    ref = _oracle_out(c)
    np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_quant_kernel_per_page_scale_extremes(kind):
    """Context whose magnitude ramps 6 orders across positions: the
    per-(row, head) scales land per PAGE in storage, and the fused
    dequant must reproduce every page's range exactly (a kernel that
    mixed up scale rows would be off by orders of magnitude, not
    epsilons)."""
    rng = np.random.default_rng(7)
    segs = [(12, 1), (9, 2)]
    mags = (10.0 ** rng.uniform(-3, 3, size=(1, 12, 1, 1))).astype(
        np.float32)
    c = _quant_case(rng, segs, kind, mags=mags)
    out = _quant_kernel_out(c, interpret=True)
    ref = _oracle_out(c)
    np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                               rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_quant_kernel_block_size_invariance(kind):
    rng = np.random.default_rng(11)
    c = _quant_case(rng, [(7, 1), (0, 5), (12, 1), (4, 6)], kind)
    ref = _quant_kernel_out(c, interpret=True)
    for q_blk, pp_blk in ((2, 1), (8, 4), (4, 8)):
        out = _quant_kernel_out(c, interpret=True, q_block=q_blk,
                                pages_per_block=pp_blk)
        np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                                   rtol=1e-5, atol=1e-6)


# -------------------------------------- quantize-at-append round trip

@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_scatter_gather_quant_roundtrip(kind):
    rng = np.random.default_rng(3)
    L, P, page, kvh, d = 2, 6, 4, 2, 8
    kp = jnp.zeros((L, P, page, kvh, d), kv_quant.storage_dtype(kind))
    vp = jnp.zeros_like(kp)
    ks = jnp.zeros((L, P, page, kvh), jnp.float32)
    vs = jnp.zeros_like(ks)
    n = 5
    k_new = jnp.asarray(rng.normal(size=(n, L, kvh, d))
                        .astype(np.float32) * 2.0)
    v_new = jnp.asarray(rng.normal(size=(n, L, kvh, d))
                        .astype(np.float32) * 2.0)
    tables = jnp.asarray(np.tile(np.array([[0, 1]], np.int32), (n, 1)))
    positions = jnp.asarray(np.arange(n, dtype=np.int32))
    valid = jnp.asarray(np.array([1, 1, 1, 1, 0], bool))
    kp, vp, ks, vs = scatter_kv_quant(kp, vp, ks, vs, k_new, v_new,
                                      tables, positions, valid, kind)
    got_k, got_v = gather_kv_quant(kp, vp, ks, vs,
                                   jnp.asarray([[0, 1]], np.int32))
    want_k = kv_quant.dequantize_rows(
        *kv_quant.quantize_rows(k_new, kind), kind=kind)
    for i in range(n):
        row = np.asarray(got_k)[:, 0, i]            # [L, kvh, d]
        if bool(valid[i]):
            np.testing.assert_allclose(row, np.asarray(want_k)[i],
                                       rtol=1e-6, atol=1e-7)
        else:
            assert float(np.abs(row).max()) == 0.0  # scratch-paged


@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_scatter_quant_write_only_append(kind):
    """Appending must not re-quantize or disturb neighbor rows: rows
    written earlier keep their exact stored bytes."""
    rng = np.random.default_rng(4)
    L, P, page, kvh, d = 1, 3, 4, 1, 8
    kp = jnp.zeros((L, P, page, kvh, d), kv_quant.storage_dtype(kind))
    vp = jnp.zeros_like(kp)
    ks = jnp.zeros((L, P, page, kvh), jnp.float32)
    vs = jnp.zeros_like(ks)

    def append(kp, vp, ks, vs, pos):
        kn = jnp.asarray(rng.normal(size=(1, L, kvh, d))
                         .astype(np.float32))
        return scatter_kv_quant(
            kp, vp, ks, vs, kn, kn,
            jnp.asarray([[0, 1]], np.int32),
            jnp.asarray([pos], np.int32), jnp.ones(1, bool), kind)

    kp, vp, ks, vs = append(kp, vp, ks, vs, 0)
    before = np.asarray(kp[0, 0, 0]).copy()
    sbefore = np.asarray(ks[0, 0, 0]).copy()
    kp, vp, ks, vs = append(kp, vp, ks, vs, 1)
    np.testing.assert_array_equal(np.asarray(kp[0, 0, 0]), before)
    np.testing.assert_array_equal(np.asarray(ks[0, 0, 0]), sbefore)


# ------------------------------------------------------- engine level

_COMMON = dict(model="debug", num_pages=64, page_size=4,
               max_batch_size=3)
_PROMPTS = [[1, 2, 3, 4, 5, 6, 7, 8], [9, 8, 7, 6, 5, 4, 3, 2],
            [11, 12, 13, 14, 15, 16, 17, 18]]


def _run(eng):
    while eng.has_work():
        eng.step()


def _mk(kind, **kw):
    c = dict(_COMMON)
    c.update(kw)
    eng = InferenceEngine(EngineConfig(kv_dtype=kind, **c))
    reqs = [Request(f"q{i}", list(p), SamplingParams(max_tokens=20))
            for i, p in enumerate(_PROMPTS)]
    for r in reqs:
        eng.add_request(r)
    return eng, reqs


def test_engine_rejects_quant_composition():
    with pytest.raises(ValueError):
        InferenceEngine(EngineConfig(model="debug", kv_dtype="int4"))
    with pytest.raises(ValueError):
        InferenceEngine(EngineConfig(model="debug", kv_dtype="int8",
                                     unified_step=False))


@pytest.mark.parametrize("kind", QUANT_KINDS)
def test_quant_preempt_restore_token_exact_vs_same_kind_oracle(kind):
    """THE quantized-hierarchy gate: quantization legitimately changes
    tokens, so the oracle is a never-preempted engine of the SAME
    kind — spill/restore must move the narrow pages + scales
    bit-exact and resume the identical stream."""
    ora, oreqs = _mk(kind)
    _run(ora)
    eng, reqs = _mk(kind, enable_kv_offload=True)
    while len(reqs[1].output_tokens) < 5:
        eng.step()
    assert eng.preempt("q1", reason="manual")
    assert eng.host_tier.spills_total == 1
    parked = eng.host_tier.entries()[0]
    assert parked.kv_kind == kind
    _run(eng)
    assert eng.host_tier.restores_total == 1
    for o, r in zip(oreqs, reqs):
        assert o.output_tokens == r.output_tokens, r.request_id


def test_quant_parked_payload_bytes_count_scales():
    eng, reqs = _mk("int8", enable_kv_offload=True)
    while len(reqs[1].output_tokens) < 3:
        eng.step()
    assert eng.preempt("q1", reason="manual")
    parked = eng.host_tier.entries()[0]
    assert parked.kv_kind == "int8"
    assert parked.k_scales_pending is not None or (
        parked.k_scales_host is not None)
    # values (1 B) + scales (4 B/head) per token row, k and v, every
    # layer — exactly the engine's configured page byte size
    mc = eng.model_cfg
    row = 2 * mc.n_layers * kv_quant.token_row_bytes(
        "int8", mc.n_kv_heads, mc.head_dim)
    want = parked.n_pages * row * _COMMON["page_size"]
    assert parked.payload_bytes() == want
    assert eng.host_tier.used_bytes == want
    assert want == parked.n_pages * eng.stats()["kv_page_bytes"]


def test_quant_session_migration_token_exact_and_kind_gated():
    """Disagg-handoff gate: export on one int8 engine, ship through
    the v2 wire, import on another — token-exact vs an uninterrupted
    same-kind engine; the same frame is REJECTED by engines of any
    other kind (engine-level ValueError, transport-level
    TransportError)."""
    kind = "int8"
    e1 = InferenceEngine(EngineConfig(kv_dtype=kind,
                                      enable_kv_offload=True,
                                      **_COMMON))
    r = Request("mig", list(_PROMPTS[0]), SamplingParams(max_tokens=20))
    e1.add_request(r)
    for _ in range(8):
        e1.step()
    assert e1.preempt("mig", reason="ship")
    state = e1.export_session("mig")
    assert state["kv_dtype"] == kind
    assert state["k_scales"].shape == state["k"].shape[:-1]
    blob = kvt.encode_session(state)
    shipped = kvt.decode_session(blob)
    assert shipped["k"].tobytes() == np.ascontiguousarray(
        state["k"]).tobytes()
    assert shipped["k_scales"].tobytes() == np.ascontiguousarray(
        state["k_scales"]).tobytes()

    e2 = InferenceEngine(EngineConfig(kv_dtype=kind,
                                      enable_kv_offload=True,
                                      **_COMMON))
    req2 = e2.import_session(shipped)
    _run(e2)
    e3 = InferenceEngine(EngineConfig(kv_dtype=kind, **_COMMON))
    r3 = Request("mig", list(_PROMPTS[0]), SamplingParams(max_tokens=20))
    e3.add_request(r3)
    _run(e3)
    assert req2.output_tokens == r3.output_tokens

    for other in ("f32", "fp8"):
        bad = InferenceEngine(EngineConfig(kv_dtype=other,
                                           enable_kv_offload=True,
                                           **_COMMON))
        with pytest.raises(ValueError):
            bad.import_session(dict(shipped))
        with pytest.raises(kvt.TransportError):
            kvt.ship_kind_compatible(shipped["kv_dtype"], other)


def test_quant_prefix_export_import_and_kind_gate():
    kind = "int8"
    sys_prefix = list(range(2, 2 + 16))          # 4 full pages
    a = InferenceEngine(EngineConfig(kv_dtype=kind, **_COMMON))
    ra = Request("p0", sys_prefix + [100, 101, 102],
                 SamplingParams(max_tokens=4))
    a.add_request(ra)
    _run(a)
    exp = a.export_prefix(sys_prefix)
    assert exp is not None and exp["kv_dtype"] == kind
    assert exp["k_scales"].shape == exp["k"].shape[:-1]
    pfx = kvt.decode_prefix(kvt.encode_prefix(
        exp["tokens"], exp["k"], exp["v"], k_scales=exp["k_scales"],
        v_scales=exp["v_scales"], kv_dtype=kind))
    assert pfx["kv_dtype"] == kind

    b = InferenceEngine(EngineConfig(kv_dtype=kind, **_COMMON))
    assert b.import_prefix(pfx["tokens"], pfx["k"], pfx["v"],
                           k_scales=pfx["k_scales"],
                           v_scales=pfx["v_scales"],
                           kv_dtype=kind) == 4
    # token-exact continuation vs an engine that prefilled it itself
    suffix = [110, 111, 112]
    rb = Request("pb", sys_prefix + suffix,
                 SamplingParams(max_tokens=8))
    b.add_request(rb)
    _run(b)
    ora = InferenceEngine(EngineConfig(kv_dtype=kind, **_COMMON))
    ro = Request("po", sys_prefix + suffix,
                 SamplingParams(max_tokens=8))
    ora.add_request(ro)
    _run(ora)
    assert rb.output_tokens == ro.output_tokens

    c = InferenceEngine(EngineConfig(**_COMMON))       # f32 engine
    with pytest.raises(ValueError):
        c.import_prefix(pfx["tokens"], pfx["k"], pfx["v"],
                        k_scales=pfx["k_scales"],
                        v_scales=pfx["v_scales"], kv_dtype=kind)


def test_quant_stats_report_configured_dtype_bytes():
    mc = llama.config("debug")
    row_f32 = (2 * mc.n_layers * mc.n_kv_heads * mc.head_dim
               * jnp.dtype(mc.dtype).itemsize)
    row_i8 = 2 * mc.n_layers * kv_quant.token_row_bytes(
        "int8", mc.n_kv_heads, mc.head_dim)
    for kind, row in (("f32", row_f32), ("int8", row_i8)):
        eng, _ = _mk(kind)
        for _ in range(3):
            eng.step()
        st = eng.stats()
        assert st["kv_dtype"] == kind
        assert st["kv_page_bytes"] == row * _COMMON["page_size"]
        assert st["kv_device_bytes_used"] == (
            eng.allocator.used_pages * st["kv_page_bytes"])


def test_cost_model_kv_dtype_parametrization():
    from ray_tpu.llm._internal.perfmodel import CostModel
    cfg = dataclasses.replace(llama.config("debug"),
                              dtype=jnp.float32)
    f32 = CostModel(cfg, page_size=8)
    for kind in QUANT_KINDS:
        q = CostModel(cfg, page_size=8, kv_dtype=kind)
        assert (f32.kv_bytes_per_token / q.kv_bytes_per_token) >= 1.9
        # scale overhead is real traffic: narrower than f32, wider
        # than values alone
        values_only = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
        assert q.kv_bytes_per_token > values_only
        assert q.page_bytes == q.kv_bytes_per_token * 8
    with pytest.raises(ValueError):
        CostModel(cfg, page_size=8, kv_dtype="int4")


# --------------------------------------------------------- wire v2

def _int8_session_frame():
    e1 = InferenceEngine(EngineConfig(kv_dtype="int8",
                                      enable_kv_offload=True,
                                      **_COMMON))
    r = Request("w", list(_PROMPTS[0]), SamplingParams(max_tokens=12))
    e1.add_request(r)
    for _ in range(8):
        e1.step()
    e1.preempt("w", reason="ship")
    return kvt.encode_session(e1.export_session("w"))


def test_wire_v2_corruption_over_scale_region():
    """crc32 covers the scale arrays too: flipping any byte across
    the scale region (the tail arrays of a v2 quant frame) raises the
    transport-error family, never garbage pages."""
    blob = _int8_session_frame()
    st = kvt.decode_session(blob)
    scale_bytes = st["k_scales"].nbytes + st["v_scales"].nbytes
    scale_start = len(blob) - 4 - scale_bytes
    for off in (scale_start, scale_start + scale_bytes // 3,
                scale_start + scale_bytes // 2,
                len(blob) - 5):
        bad = bytearray(blob)
        bad[off] ^= 0xFF
        with pytest.raises(kvt.TransportError):
            kvt.decode_session(bytes(bad))


def test_wire_v1_frames_still_decode_as_f32():
    rng = np.random.default_rng(5)
    k = rng.standard_normal((2, 2, 4, 2, 8)).astype(np.float32)
    orig = kvt.WIRE_VERSION
    kvt.WIRE_VERSION = 1
    try:
        blob = kvt.encode_prefix([1, 2, 3], k, k)
    finally:
        kvt.WIRE_VERSION = orig
    pfx = kvt.decode_prefix(blob)
    assert pfx["kv_dtype"] == "f32"
    assert pfx["k_scales"] is None and pfx["v_scales"] is None
    assert pfx["k"].tobytes() == k.tobytes()
    with pytest.raises(kvt.TransportError):
        # an unknown FUTURE version still refuses
        kvt.WIRE_VERSION = 9
        try:
            bad = kvt.encode_prefix([1], k, k)
        finally:
            kvt.WIRE_VERSION = orig
        kvt.decode_prefix(bad)


def test_wire_v2_inconsistent_quant_frames_rejected():
    rng = np.random.default_rng(6)
    k = rng.integers(-127, 127, (2, 2, 4, 2, 8)).astype(np.int8)
    s = np.abs(rng.standard_normal((2, 2, 4, 2))).astype(np.float32)
    # quant frame missing its scales
    with pytest.raises(kvt.TransportError):
        kvt.decode_prefix(kvt.encode_prefix([1], k, k,
                                            kv_dtype="int8"))
    # scale shape disagreeing with the pages
    with pytest.raises(kvt.TransportError):
        kvt.decode_prefix(kvt.encode_prefix(
            [1], k, k, k_scales=s[:, :1], v_scales=s,
            kv_dtype="int8"))
    # f32 frame smuggling scale arrays
    kf = k.astype(np.float32)
    with pytest.raises(kvt.TransportError):
        kvt.decode_prefix(kvt.encode_prefix(
            [1], kf, kf, k_scales=s, v_scales=s, kv_dtype="f32"))


# ---------------------------------------------- quantized collectives

def _tp_mesh(n=4):
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


@pytest.mark.parametrize("kind,psum_tol,ag_tol", [
    ("int8", 0.02, 0.01), ("fp8", 0.08, 0.05), ("f32", 1e-6, 1e-6),
])
def test_quantized_collectives_match_f32_oracle(kind, psum_tol,
                                                ag_tol):
    """EQuARX tolerance oracle: both hops quantized, error bounded
    per kind vs the lax collectives on a 4-device tp mesh (f32 pass-
    through is exact)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _tp_mesh()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 37, 19)).astype(np.float32)
                    * 3.0)

    got = shard_map(functools.partial(qcoll.quantized_psum,
                                      axis_name="tp", kind=kind),
                    mesh, in_specs=P("tp"), out_specs=P("tp"))(x)
    want = shard_map(lambda v: jax.lax.psum(v, "tp"), mesh,
                     in_specs=P("tp"), out_specs=P("tp"))(x)
    rel = float(jnp.linalg.norm(got - want) / jnp.linalg.norm(want))
    assert rel < psum_tol, (kind, rel)

    got = shard_map(functools.partial(qcoll.quantized_all_gather,
                                      axis_name="tp", kind=kind),
                    mesh, in_specs=P("tp"),
                    out_specs=P(None, "tp"))(x)
    want = shard_map(lambda v: jax.lax.all_gather(v, "tp"), mesh,
                     in_specs=P("tp"), out_specs=P(None, "tp"))(x)
    rel = float(jnp.linalg.norm(got - want) / jnp.linalg.norm(want))
    assert rel < ag_tol, (kind, rel)


def test_quantized_collective_payload_accounting():
    n = 37 * 19
    assert qcoll.payload_bytes(n, "f32") == n * 4
    blocks = -(-n // qcoll.DEFAULT_BLOCK)
    assert qcoll.payload_bytes(n, "int8") == n + blocks * 4
    assert (qcoll.payload_bytes(n, "f32")
            / qcoll.payload_bytes(n, "int8")) >= 3.5


def test_engine_quantized_collectives_knob():
    """The config knob arms the ops-layer helpers; it must construct
    cleanly beside kv_dtype (the llama path is GSPMD — no call site
    swaps, correctness is the oracle above)."""
    eng = InferenceEngine(EngineConfig(
        model="debug", kv_dtype="int8", quantized_collectives=True,
        num_pages=32, page_size=4, max_batch_size=2))
    out = eng.generate([[1, 2, 3, 4]], SamplingParams(max_tokens=4))
    assert len(out[0].output_tokens) == 4
