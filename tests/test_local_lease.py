"""Daemon-local lease granting (distributed dispatch — reference
parity: the raylet grants worker leases locally with no GCS round-trip,
src/ray/raylet/local_task_manager.h:102; spillback routes the client to
the controller's global scheduler, cluster_task_manager.h:45)."""

import asyncio
import time

import pytest

import ray_tpu


def _runtime():
    import ray_tpu._private.worker as worker_mod
    return worker_mod._runtime


@pytest.fixture()
def fresh_cluster():
    # Force-enable: the default "auto" turns local granting off when
    # the controller shares the daemon's host (this box), since the
    # path only pays off by removing a cross-host hop.
    from ray_tpu._private.config import get_config
    cfg = get_config()
    prev = cfg.local_lease_enabled
    cfg.local_lease_enabled = "1"
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()
    cfg.local_lease_enabled = prev


def test_local_grants_used_and_returned(fresh_cluster):
    rt = fresh_cluster

    @ray_tpu.remote
    def sq(x):
        return x * x

    assert ray_tpu.get([sq.remote(i) for i in range(100)]) == \
        [i * i for i in range(100)]
    daemon = rt.head_daemon
    assert daemon.local_leases_granted > 0, \
        "lease storm never used the local-daemon grant path"
    # idle shrink: delegated slots flow back to the controller and the
    # scheduled path sees full availability again
    deadline = time.time() + 25
    while time.time() < deadline and (
            daemon._lease_blocks or rt.controller.delegations):
        time.sleep(0.25)
    assert not daemon._lease_blocks
    assert not rt.controller.delegations
    for n in rt.controller.nodes.values():
        assert abs(n.resources_avail["CPU"]
                   - n.resources_total["CPU"]) < 1e-6


def test_spill_falls_back_and_completes(fresh_cluster):
    """With every CPU consumed by delegation-ineligible work, local
    grants spill; the storm still completes via the scheduled path."""
    rt = fresh_cluster

    @ray_tpu.remote
    def slow():
        time.sleep(0.8)
        return 1

    @ray_tpu.remote
    def quick(x):
        return x + 1

    # 4 long tasks occupy all 4 CPUs through the normal paths, then a
    # burst of quick tasks arrives: local grants must spill (no spare
    # capacity to delegate) yet every task completes.
    long_refs = [slow.remote() for _ in range(4)]
    time.sleep(0.3)
    assert ray_tpu.get([quick.remote(i) for i in range(40)],
                       timeout=60) == list(range(1, 41))
    assert ray_tpu.get(long_refs, timeout=60) == [1, 1, 1, 1]


def test_dead_owner_lease_reaped(fresh_cluster):
    """A locally-granted lease whose owner process vanished is reaped
    by the daemon's sweep (worker killed, slot returned) — same
    refused-scoring as the controller's reaper."""
    rt = fresh_cluster
    daemon = rt.head_daemon
    daemon.LOCAL_LEASE_PROBE_AGE_S = 0.5
    daemon.LOCAL_LEASE_PROBE_PERIOD_S = 0.5
    loop = rt.loop_runner

    async def _grant():
        # owner addr nobody listens on -> connection refused on probe
        return await daemon.rpc_lease_worker_local(
            resources={"CPU": 1.0}, owner_addr=["127.0.0.1", 1])

    reply = loop.run_sync(_grant(), timeout=30)
    assert reply["status"] == "ok"
    worker_id = reply["worker_id"]
    deadline = time.time() + 20
    while time.time() < deadline and reply["lease_id"] in \
            daemon._local_leases:
        time.sleep(0.25)
    assert reply["lease_id"] not in daemon._local_leases, \
        "dead-owner lease never reaped"
    # reaped via terminate: the worker must not return to the idle pool
    handle = daemon.workers.get(worker_id)
    assert handle is None or handle.state in ("dead", "leased") \
        or handle.proc.poll() is not None


def test_pending_task_reclaims_idle_blocks(fresh_cluster):
    """A scheduled task that cannot fit while daemons hold free
    delegated slots triggers the controller's reclaim command, freeing
    the capacity well before the idle timer (spill-back pressure)."""
    rt = fresh_cluster

    @ray_tpu.remote
    def quick(x):
        return x + 1

    # storm to leave delegated blocks hot (activity keeps refreshing,
    # so the idle path alone would hold them ~10s)
    assert ray_tpu.get([quick.remote(i) for i in range(50)]) == \
        list(range(1, 51))
    assert rt.controller.delegations, "no blocks delegated by storm"

    @ray_tpu.remote(num_cpus=4)
    def wide():
        return "wide"

    # needs every CPU: placeable only after the delegation is reclaimed
    t0 = time.time()
    assert ray_tpu.get(wide.remote(), timeout=30) == "wide"
    assert time.time() - t0 < 9.0, \
        "wide task waited for the idle timer instead of the reclaim"


def test_controller_restart_reconciles_delegations(fresh_cluster):
    """Simulated controller restart: the fresh NodeEntry has no
    delegation record. The daemon re-acquires its slots (or sheds
    them), so local grants never double-book against the scheduler."""
    rt = fresh_cluster
    daemon = rt.head_daemon
    loop = rt.loop_runner

    async def _grant():
        return await daemon.rpc_lease_worker_local(
            resources={"CPU": 1.0}, owner_addr=list(rt.client.address))

    reply = loop.run_sync(_grant(), timeout=30)
    assert reply["status"] == "ok"
    free_before = sum(daemon._lease_blocks.values())
    assert free_before > 0

    async def _wipe_and_reconcile():
        # what a restart does to controller state: delegations gone,
        # node availability rebuilt from scratch
        ctrl = rt.controller
        node = ctrl.nodes[daemon.node_id]
        for _ in range(free_before + 1):     # +1 for the live lease
            node.release({"CPU": 1.0})
        ctrl.delegations.clear()
        await daemon._reconcile_delegations()

    loop.run_sync(_wipe_and_reconcile(), timeout=30)
    ctrl = rt.controller
    node = ctrl.nodes[daemon.node_id]
    # invariant restored: controller-side acquisition == daemon-side
    # (free slots + backed live leases)
    backed = sum(1 for l in daemon._local_leases.values()
                 if not l.get("unbacked"))
    delegated = sum(ctrl.delegations.values())
    assert delegated == sum(daemon._lease_blocks.values()) + backed
    assert node.resources_total["CPU"] - node.resources_avail["CPU"] \
        >= delegated - 1e-9
    loop.run_sync(
        daemon.rpc_release_lease_local(reply["lease_id"]), timeout=10)


def test_zero_cpu_tasks_never_claim_zero_cpu_blocks(fresh_cluster):
    """An explicit CPU: 0 request used to build a {"CPU": 0.0} block
    key and delegate a zero-CPU block; it must route through the
    scheduled path instead (zero entries normalize out of the key) —
    WITHOUT latching the client's process-wide local-lease-off flag
    (the refusal is 'spill', not 'unsupported')."""
    rt = fresh_cluster

    @ray_tpu.remote(num_cpus=0)
    def z(x):
        return x + 1

    assert ray_tpu.get([z.remote(i) for i in range(20)]) == \
        list(range(1, 21))
    daemon = rt.head_daemon
    assert all(dict(key).get("CPU", 0.0) > 0.0
               for key in daemon._lease_blocks), daemon._lease_blocks
    # and the controller's ledger holds no zero-CPU delegation
    for _, res in rt.controller.delegations:     # (node_id, ((k, v),...))
        assert dict(res).get("CPU", 0.0) > 0.0, res

    # regular tasks submitted AFTER the zero-cpu storm still use the
    # local-lease fast path ('spill' must not set the process-wide
    # unsupported latch; only transient per-key 5 s skips, which we
    # clear so the check is timing-independent)
    import ray_tpu._private.state as state
    client = state.current_client()
    assert not client._local_lease_unsupported, \
        "zero-cpu refusal latched local leasing off"
    client._local_lease_skip_until.clear()
    granted_before = daemon.local_leases_granted

    @ray_tpu.remote
    def sq(x):
        return x * x

    assert ray_tpu.get([sq.remote(i) for i in range(50)]) == \
        [i * i for i in range(50)]
    assert daemon.local_leases_granted > granted_before, \
        "local-lease fast path dead after zero-cpu storm"


@pytest.mark.parametrize("mode", ["0", "auto"])
def test_local_lease_off_modes(monkeypatch, mode):
    """'0' disables outright; 'auto' disables here because controller
    and daemon share a host (loopback grants lose — BENCH_CORE A/B)."""
    from ray_tpu._private.config import get_config
    monkeypatch.setattr(get_config(), "local_lease_enabled", mode)
    rt = ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def f(x):
            return x

        assert ray_tpu.get([f.remote(i) for i in range(20)]) == \
            list(range(20))
        assert rt.head_daemon.local_leases_granted == 0
    finally:
        ray_tpu.shutdown()
