"""Scale-envelope smoke tests (reference parity: release/benchmarks —
many_tasks / many_actors / many_pgs / single_node rows, shrunk to
1-core-box scale). These guard against queue/accounting blowups, not
absolute throughput."""

import time

import ray_tpu


def test_many_queued_tasks_drain(ray_start):
    @ray_tpu.remote
    def nop(i):
        return i

    n = 10000     # full 50k envelope lives in bench_envelope.py
    t0 = time.time()
    refs = [nop.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=600)
    dt = time.time() - t0
    assert out == list(range(n))
    assert dt < 300, f"{n} tasks took {dt:.0f}s"
    # resource accounting returned to zero after the storm
    deadline = time.time() + 20
    while time.time() < deadline:
        if (ray_tpu.available_resources().get("CPU")
                == ray_tpu.cluster_resources().get("CPU")):
            break
        time.sleep(0.25)
    assert (ray_tpu.available_resources().get("CPU")
            == ray_tpu.cluster_resources().get("CPU"))


def test_many_actors_lifecycle(ray_start):
    @ray_tpu.remote
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    n = 40
    actors = [A.options(num_cpus=0).remote(i) for i in range(n)]
    assert ray_tpu.get([a.who.remote() for a in actors],
                       timeout=300) == list(range(n))
    for a in actors:
        ray_tpu.kill(a)


def test_many_placement_groups(ray_start):
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    pgs = []
    for _ in range(100):
        pg = placement_group([{"CPU": 0.01}], strategy="PACK")
        assert pg.ready(timeout=60)
        pgs.append(pg)
    for pg in pgs:
        remove_placement_group(pg)
    deadline = time.time() + 20
    while time.time() < deadline:
        if (ray_tpu.available_resources().get("CPU")
                == ray_tpu.cluster_resources().get("CPU")):
            break
        time.sleep(0.25)
    assert (ray_tpu.available_resources().get("CPU")
            == ray_tpu.cluster_resources().get("CPU"))


def test_many_objects_put_get(ray_start):
    refs = [ray_tpu.put(bytes([i % 256]) * 100) for i in range(1000)]
    values = ray_tpu.get(refs, timeout=120)
    assert all(values[i][0] == i % 256 for i in range(1000))
