"""Preemptible batch-inference lane gates (ISSUE 14).

The acceptance contract: an interactive burst preempts batch jobs
token-exact vs a never-preempted oracle (greedy AND sampled), batch
jobs complete after the trough returns, interactive latency is
unchanged vs a batch-lane-off A/B, and the admission/autoscaler/
watchdog planes exclude batch-lane depth from their overload and
burn signals.
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from ray_tpu.llm._internal.engine import (EngineConfig,  # noqa: E402
                                          InferenceEngine, Request,
                                          SamplingParams)
from ray_tpu.llm._internal.server import LLMServerImpl  # noqa: E402
from ray_tpu.serve.llm import (AdmissionConfig,  # noqa: E402
                               AutoscaleConfig, BatchLaneConfig,
                               FleetAutoscaler, FleetManager,
                               FleetMetrics, LocalReplicaClient,
                               ReplicaSnapshot)
from ray_tpu.serve.llm.batch import (BATCH_PRIORITY,  # noqa: E402
                                     INTERACTIVE_PRIORITY)


def _engine(**kw):
    cfg = dict(model="debug", max_batch_size=2, num_pages=64,
               page_size=16, enable_kv_offload=True,
               host_kv_pages=256, kv_watermark_tokens=16,
               enable_metrics=True, enable_blackbox=False, seed=0)
    cfg.update(kw)
    return InferenceEngine(EngineConfig(**cfg))


def _req(rid, prompt, out=24, priority=0, lane="interactive",
         temperature=0.0, seed=None):
    return Request(rid, list(prompt),
                   SamplingParams(max_tokens=out,
                                  temperature=temperature,
                                  seed=seed),
                   priority=priority, lane=lane)


# ------------------------------------------------- engine-level gates
@pytest.mark.parametrize("temperature,seed", [(0.0, None),
                                              (0.9, 1234)])
def test_interactive_preempts_batch_token_exact(temperature, seed):
    """THE gate: batch jobs fill the engine, an interactive burst
    preempts them (slot-priority preemption + spill), everyone
    completes, and the batch outputs are byte-identical to a
    never-preempted oracle — greedy and sampled."""
    def batch_reqs():
        return [_req(f"b{i}", range(2 + 4 * i, 10 + 4 * i), out=32,
                     priority=BATCH_PRIORITY, lane="batch",
                     temperature=temperature, seed=seed)
                for i in range(2)]

    eng = _engine()
    bs = batch_reqs()
    for r in bs:
        eng.add_request(r)
    for _ in range(6):
        eng.step()                      # decoding mid-flight
    burst = [_req(f"i{i}", range(40 + 8 * i, 46 + 8 * i), out=8,
                  priority=INTERACTIVE_PRIORITY,
                  temperature=temperature, seed=seed)
             for i in range(2)]
    for r in burst:
        eng.add_request(r)
    while not all(r.finished for r in burst):
        eng.step()
    # the burst claimed its slots by preempting batch work
    assert eng.preempt_counts.get("priority", 0) >= 1
    assert eng.host_tier.spills_total >= 1
    # trough: batch completes
    for _ in range(3000):
        if all(r.finished for r in bs):
            break
        eng.step()
    assert all(r.finished for r in bs)
    assert eng.host_tier.restores_total >= 1

    oracle = _engine()
    obs = batch_reqs()
    for r in obs:
        oracle.add_request(r)
    while not all(r.finished for r in obs):
        oracle.step()
    for got, want in zip(bs, obs):
        assert got.output_tokens == want.output_tokens, (
            temperature, got.request_id)


def test_parked_batch_never_blocks_interactive_admission():
    """The inversion guard: with a batch session PARKED (spilled),
    a fresh interactive request must admit past it instead of
    waiting for the restore (pre-ISSUE-14 parked-first would
    block)."""
    eng = _engine()
    bs = [_req(f"b{i}", range(2 + 4 * i, 10 + 4 * i), out=48,
               priority=BATCH_PRIORITY, lane="batch")
          for i in range(2)]
    for r in bs:
        eng.add_request(r)
    for _ in range(6):
        eng.step()
    first = _req("i0", range(60, 66), out=8,
                 priority=INTERACTIVE_PRIORITY)
    eng.add_request(first)
    while not first.finished:
        eng.step()
    assert len(eng.parked) >= 1        # batch is parked now
    nxt = _req("i1", range(70, 76), out=8,
               priority=INTERACTIVE_PRIORITY)
    eng.add_request(nxt)
    ticks = 0
    while not nxt.finished and ticks < 200:
        eng.step()
        ticks += 1
    assert nxt.finished and nxt.finish_reason == "length"
    # and the batch work still completes in the trough
    for _ in range(3000):
        if all(r.finished for r in bs):
            break
        eng.step()
    assert all(r.finished for r in bs)


def test_prefilling_batch_victim_requeues_behind_its_preemptor():
    """Review-hardening gate: a still-PREFILLING batch victim
    requeues (PR 10: no tokens emitted, nothing to spill) — but it
    must land BEHIND the interactive head that preempted it, not at
    waiting[0] where the very next admission would hand it the slot
    back (priority inversion; with prefix caching off, a
    preempt/readmit livelock that starves both requests forever)."""
    eng = _engine(enable_prefix_caching=False, max_batch_size=1,
                  max_prefill_tokens=16)
    b = _req("b0", range(2, 2 + 64), out=16, lane="batch",
             priority=BATCH_PRIORITY)
    eng.add_request(b)
    eng.step()                       # b0 holds the slot, prefilling
    assert any(s.request is b and not s.ready for s in eng.slots)
    i = _req("i0", range(100, 106), out=4,
             priority=INTERACTIVE_PRIORITY)
    eng.add_request(i)
    ticks = 0
    while not i.finished and ticks < 200:
        eng.step()
        ticks += 1
    assert i.finished and i.finish_reason == "length", (
        "interactive starved behind the batch victim it preempted")
    assert eng.preempt_counts.get("priority", 0) >= 1
    # and the requeued victim still completes in the trough
    ticks = 0
    while not b.finished and ticks < 2000:
        eng.step()
        ticks += 1
    assert b.finished and b.finish_reason == "length"


def test_parked_gate_is_per_head_not_unlocked_by_first_head():
    """Review-hardening gate: an interactive head outranking the
    parked work admits past it — but a BATCH request queued behind
    that head must NOT ride through the opened gate and claim the
    pages the earlier-arrived parked session needs (the PR 10
    parked-first invariant is per head, not per _admit call)."""
    eng = _engine()
    residents = [_req(f"b{i}", range(2 + 4 * i, 10 + 4 * i), out=48,
                      priority=BATCH_PRIORITY, lane="batch")
                 for i in range(2)]
    for r in residents:
        eng.add_request(r)
    for _ in range(6):
        eng.step()
    first = _req("i0", range(60, 66), out=8,
                 priority=INTERACTIVE_PRIORITY)
    eng.add_request(first)
    while not first.finished:
        eng.step()
    assert len(eng.parked) >= 1           # a batch resident spilled
    parked_ids = {p.request.request_id for p in eng.parked}
    # now an interactive head + a NEW batch request behind it
    i1 = _req("i1", range(70, 76), out=8,
              priority=INTERACTIVE_PRIORITY)
    late_batch = _req("b9", range(80, 88), out=8,
                      priority=BATCH_PRIORITY, lane="batch")
    eng.add_request(i1)
    eng.add_request(late_batch)
    eng.step()
    # the interactive head admitted; the late batch request did NOT
    # jump the parked session through the head's exception
    assert any(s.request is i1 for s in eng.slots)
    assert not any(s.request is late_batch for s in eng.slots)
    assert late_batch in eng.waiting
    # everyone still completes, parked-first order preserved for the
    # batch tier: the PARKED session resumes before the late one runs
    order = []
    seen = set()
    for _ in range(4000):
        if all(r.finished for r in (*residents, late_batch, i1)):
            break
        eng.step()
        for s in eng.slots:
            req = s.request
            if req is not None and req.lane == "batch" \
                    and req.request_id not in seen:
                seen.add(req.request_id)
                order.append(req.request_id)
    assert all(r.finished for r in (*residents, late_batch))
    resumed = [rid for rid in order if rid in parked_ids]
    assert resumed, "the parked session never resumed"
    assert order.index(resumed[0]) < order.index("b9"), order


def test_mixed_priority_parked_fifo_never_livelocks():
    """Review-hardening gate (confirmed livelock pre-fix): parked
    FIFO = [batch p0, interactive p1] with an interactive request
    waiting. The restore yield must SKIP the outranked batch head
    and restore the parked interactive behind it — a `break` there
    plus _admit's all-parked gate meant nothing restored and nothing
    admitted, forever."""
    eng = _engine()
    b = _req("b0", range(2, 10), out=48, lane="batch",
             priority=BATCH_PRIORITY)
    i0 = _req("i0", range(20, 28), out=48,
              priority=INTERACTIVE_PRIORITY)
    eng.add_request(b)
    eng.add_request(i0)
    for _ in range(6):
        eng.step()
    # park BOTH, batch first (FIFO head), interactive behind it
    assert eng.preempt("b0", reason="test")
    assert eng.preempt("i0", reason="test")
    ids = [p.request.request_id for p in eng.parked]
    assert ids == ["b0", "i0"]
    # a fresh interactive request arrives: it outranks b0 but NOT i0
    i1 = _req("i1", range(40, 46), out=8,
              priority=INTERACTIVE_PRIORITY)
    eng.add_request(i1)
    ticks = 0
    while not i1.finished and ticks < 400:
        eng.step()
        ticks += 1
    assert i1.finished, "mixed-priority parked FIFO livelocked"
    # and everything else still completes
    for _ in range(4000):
        if b.finished and i0.finished:
            break
        eng.step()
    assert b.finished and i0.finished


def test_fleet_clamps_client_priority_above_batch_tier():
    """Review-hardening gate: with the lane on, a client explicitly
    sending the pre-lane default priority 0 must be clamped UP — it
    would otherwise tie with batch jobs and never preempt them."""

    async def main():
        clients = [LocalReplicaClient("r0", _server("r0"))]
        fleet = _fleet(clients, lane=True)
        body, _ = fleet._trace_begin("completions",
                                     {"prompt": "x", "priority": 0})
        assert body["priority"] == INTERACTIVE_PRIORITY
        body2, _ = fleet._trace_begin("completions",
                                      {"prompt": "x", "priority": 3})
        assert body2["priority"] == 3           # tiers above survive
        bb, _ = fleet._trace_begin("completions",
                                   {"prompt": "x", "priority": 9},
                                   lane="batch")
        assert bb["priority"] == BATCH_PRIORITY  # forced down
        off = _fleet(clients, lane=False)
        body3, _ = off._trace_begin("completions", {"prompt": "x"})
        assert "priority" not in body3           # lane off: untouched
        await fleet.stop()

    asyncio.run(main())


def test_autoscaler_occupancy_excludes_batch_pages():
    """Review-hardening gate: a batch-soaked engine reports its
    displaceable page share, and the snapshot's interactive
    occupancy (the autoscaler's idle signal) excludes it — a fleet
    full of priority-0 work must still read as scale-downable."""
    eng = _engine()
    bs = [_req(f"b{i}", range(2 + 4 * i, 10 + 4 * i), out=48,
               priority=BATCH_PRIORITY, lane="batch")
          for i in range(2)]
    for r in bs:
        eng.add_request(r)
    for _ in range(6):
        eng.step()
    lanes = eng.lane_counts()
    assert lanes["batch_kv_pages"] > 0
    snap = ReplicaSnapshot.from_stats({
        "replica": "r0", "kv_occupancy": 0.8,
        "kv_occupancy_batch": 0.75})
    assert abs(snap.interactive_occupancy() - 0.05) < 1e-9
    for r in bs:
        eng.abort(r.request_id)


def test_batch_job_cancel():
    """POST /v1/batch/{id}/cancel semantics: unlaunched requests
    stop, completed results are kept, the pump drains cleanly."""

    async def main():
        clients = [LocalReplicaClient("r0", _server("r0"))]
        fleet = _fleet(clients, lane=True)
        await fleet.refresh()
        brief = fleet.batch.submit({"requests": [
            {"prompt": f"bulk {i}", "max_tokens": 8}
            for i in range(8)]})
        # let a couple launch, then cancel
        for _ in range(200):
            await asyncio.sleep(0.01)
            if fleet.batch.completed_requests >= 1:
                break
        doc = fleet.batch.cancel(brief["id"])
        assert doc["status"] == "cancelled"
        # pump drains: in-flight requests finish, queued ones never
        # launch
        for _ in range(400):
            await asyncio.sleep(0.01)
            if fleet.batch.inflight == 0 \
                    and fleet.batch._work.empty():
                break
        final = fleet.batch.get(brief["id"])
        assert final["status"] == "cancelled"
        assert 1 <= final["completed"] < 8
        done = [r for r in final["results"] if r is not None]
        assert len(done) == final["completed"]
        assert fleet.batch.cancel("nope") is None
        await fleet.stop()
        for c in clients:
            if c.server._pump is not None:
                c.server._pump.cancel()

    asyncio.run(main())


def test_cancel_is_final_even_when_all_requests_were_in_flight():
    """A job whose every request was already launched at cancel time
    must stay CANCELLED when the in-flight stragglers run to
    completion — _maybe_finish must not resurrect it as 'done' (the
    results themselves are kept)."""

    async def main():
        clients = [LocalReplicaClient("r0", _server("r0"))]
        fleet = _fleet(clients, lane=True)
        await fleet.refresh()
        brief = fleet.batch.submit({"requests": [
            {"prompt": f"bulk {i}", "max_tokens": 24}
            for i in range(2)]})
        # wait until BOTH are in flight (queue drained, none done)
        for _ in range(800):
            await asyncio.sleep(0.005)
            if fleet.batch._work.empty() \
                    and fleet.batch.inflight == 2:
                break
        doc = fleet.batch.cancel(brief["id"])
        if doc["status"] == "cancelled":     # lost the race = no-op
            for _ in range(800):
                await asyncio.sleep(0.01)
                if fleet.batch.inflight == 0:
                    break
            final = fleet.batch.get(brief["id"])
            assert final["status"] == "cancelled"
            kept = [r for r in final["results"] if r is not None]
            assert len(kept) == final["completed"]
        await fleet.stop()
        for c in clients:
            if c.server._pump is not None:
                c.server._pump.cancel()

    asyncio.run(main())


def test_equal_priority_never_preempts():
    """The pre-ISSUE-14 contract holds: equal-priority requests do
    head-of-line queueing, never preemption."""
    eng = _engine()
    residents = [_req(f"r{i}", range(2 + 4 * i, 10 + 4 * i), out=16)
                 for i in range(2)]
    for r in residents:
        eng.add_request(r)
    for _ in range(4):
        eng.step()
    peer = _req("peer", range(40, 46), out=8)     # same priority 0
    eng.add_request(peer)
    while not peer.finished:
        eng.step()
    assert eng.preempt_counts.get("priority", 0) == 0


def test_batch_lane_excluded_from_slo_totals():
    """Engine telemetry: batch-lane requests produce NO SLO
    observations (the watchdog/autoscaler inputs) while their tokens
    land in the batch counters."""
    eng = _engine()
    b = _req("b0", range(2, 10), out=8, lane="batch",
             priority=BATCH_PRIORITY)
    i = _req("i0", range(20, 26), out=8,
             priority=INTERACTIVE_PRIORITY)
    eng.add_request(b)
    eng.add_request(i)
    while not (b.finished and i.finished):
        eng.step()
    tot = eng.telemetry.slo_totals()
    assert tot["ttft_n"] == 1.0            # the interactive one only
    assert tot["queue_n"] == 1.0
    assert tot["e2e_n"] == 1.0
    summary = eng.telemetry.summary()
    assert summary["batch"]["generated_tokens"] == 8
    assert summary["batch"]["finished"] == {"length": 1}
    lanes = eng.lane_counts()
    assert lanes == {"waiting_batch": 0, "active_batch": 0,
                     "parked_batch": 0, "batch_kv_pages": 0}


def test_lane_rides_session_export_wire():
    """A migrated batch session stays batch on the importer (its SLO
    exclusion and victim priority must survive the hop)."""
    eng = _engine()
    b = _req("b0", range(2, 10), out=32, lane="batch",
             priority=BATCH_PRIORITY)
    eng.add_request(b)
    for _ in range(6):
        eng.step()
    state = eng.export_session("b0", "test")
    assert state is not None and state["lane"] == "batch"
    dst = _engine()
    req = dst.import_session(state)
    assert req.lane == "batch"
    assert req.priority == BATCH_PRIORITY


# ------------------------------------------------ control-plane gates
def test_autoscaler_ignores_batch_backlog():
    """A deep batch-lane queue must not breach the autoscaler while
    the same depth of interactive work must."""
    auto = FleetAutoscaler(AutoscaleConfig(
        min_replicas=1, max_replicas=4, upscale_delay_s=0.0))
    # all waiting is batch: FleetManager subtracts it => waiting=0
    m = FleetMetrics(waiting=0)
    assert auto.decide(m, active=2, now=100.0) == 2
    # the same depth interactive breaches (waiting > active)
    auto2 = FleetAutoscaler(AutoscaleConfig(
        min_replicas=1, max_replicas=4, upscale_delay_s=0.0))
    m2 = FleetMetrics(waiting=12)
    assert auto2.decide(m2, active=2, now=100.0) == 3


def test_router_treats_batch_depth_as_displaceable():
    """A replica soaking a deep batch queue still takes its affinity
    traffic (batch depth subtracted from the saturation check)."""
    from ray_tpu.serve.llm import FleetRouter, RouterConfig
    r = FleetRouter(RouterConfig(spill_waiting=4))
    r.set_replicas(["r0", "r1"])
    snaps = {
        "r0": ReplicaSnapshot(replica="r0", waiting=10,
                              waiting_batch=10),
        "r1": ReplicaSnapshot(replica="r1", waiting=0),
    }
    fp = "some-prefix"
    want = r.ring.preferred(fp)[0]
    rid, outcome = r.pick_ex(fp, snaps, {})
    assert rid == want and outcome == "affinity"


def test_snapshot_parses_lane_counts():
    snap = ReplicaSnapshot.from_stats(
        {"replica": "r0", "waiting": 7, "waiting_batch": 5,
         "active": 4, "active_batch": 3})
    assert snap.waiting_batch == 5 and snap.active_batch == 3


# ------------------------------------------------------ fleet-level A/B
def _server(rid):
    return LLMServerImpl({
        "model_id": "m", "model_source": "debug",
        "engine_kwargs": {"max_batch_size": 2, "num_pages": 64,
                          "page_size": 16, "enable_kv_offload": True,
                          "kv_watermark_tokens": 16,
                          "host_kv_pages": 256,
                          "metrics_replica_id": rid,
                          "enable_blackbox": False}})


def _fleet(clients, lane):
    return FleetManager(
        clients,
        admission=AdmissionConfig(max_concurrent=8, max_queue=32),
        batch_lane=(BatchLaneConfig(max_inflight=2) if lane
                    else None))


def test_batch_routes_through_serve_app():
    """The HTTP surface: FleetConfig(batch_lane=...) ->
    build_llm_fleet_app -> POST /v1/batch submits, GET /v1/batch and
    /v1/batch/{id} report, and the job completes through the lane."""
    import json
    import time
    import uuid

    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.serve._private.proxy import Request as HttpRequest
    from ray_tpu.serve.llm import FleetConfig, build_llm_fleet_app

    tag = f"batchapp{uuid.uuid4().hex[:8]}"
    app = build_llm_fleet_app(FleetConfig(
        llm_config=LLMConfig(
            model_id="mb", model_source="debug",
            engine_kwargs=dict(max_batch_size=4, page_size=8,
                               num_pages=96, seed=7,
                               prefill_buckets=(16, 32),
                               metrics_model_id=tag)),
        min_replicas=1, max_replicas=1,
        admission=AdmissionConfig(max_concurrent=4, max_queue=8),
        batch_lane=BatchLaneConfig(max_inflight=2)))
    try:
        h = serve.run(app, name="batch-local",
                      local_testing_mode=True)

        def req(method, path, body=b""):
            return HttpRequest(method, path, {}, {}, body)

        brief = h.remote(req(
            "POST", "/v1/batch",
            json.dumps({"requests": [
                {"prompt": f"bulk {i}", "max_tokens": 4}
                for i in range(3)]}).encode())).result(timeout_s=180)
        assert brief["object"] == "batch" and brief["total"] == 3
        jid = brief["id"]
        deadline = time.monotonic() + 120
        doc = None
        while time.monotonic() < deadline:
            doc = h.remote(req("GET", f"/v1/batch/{jid}")).result(
                timeout_s=60)
            if doc["status"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert doc is not None and doc["status"] == "done", doc
        assert doc["completed"] == 3
        assert all(r["usage"]["completion_tokens"] == 4
                   for r in doc["results"])
        lst = h.remote(req("GET", "/v1/batch")).result(timeout_s=60)
        assert [j["id"] for j in lst["data"]] == [jid]
        assert lst["lane"]["recovered_tokens"] == 12
        missing = h.remote(req("GET", "/v1/batch/nope")).result(
            timeout_s=60)
        assert getattr(missing, "status", 200) == 404
    finally:
        serve.shutdown()


def test_fleet_batch_ab_recovers_tokens_without_regression():
    """The fleet A/B the bench gate mirrors: identical interactive
    traffic with the lane off vs on (plus a bulk backlog). The lane
    must recover batch tokens > 0, complete every job, keep every
    interactive latency sane, and keep the front door
    interactive-only."""
    def run(lane: bool):
        clients = [LocalReplicaClient(r, _server(r))
                   for r in ("r0", "r1")]
        fleet = _fleet(clients, lane)

        async def drive():
            await fleet.refresh()
            if lane:
                fleet.batch.submit({"requests": [
                    {"prompt": f"bulk {i}", "max_tokens": 16}
                    for i in range(6)]})
            outs = []
            for wave in range(3):
                outs += await asyncio.gather(*[
                    fleet.dispatch(
                        "completions",
                        {"prompt": f"wave {wave} user {i}",
                         "max_tokens": 8})
                    for i in range(4)])
                await asyncio.sleep(0.05)
            job = None
            if lane:
                for _ in range(800):
                    await asyncio.sleep(0.02)
                    await fleet.refresh()
                    job = fleet.batch.get("batch-1")
                    if job["status"] in ("done", "failed"):
                        break
            await fleet.stop()
            for c in clients:
                if c.server._pump is not None:
                    c.server._pump.cancel()
            return outs, job, fleet

        return asyncio.run(drive())

    outs_off, _, fleet_off = run(False)
    outs_on, job, fleet_on = run(True)
    # identical interactive traffic, identical outputs (the lane may
    # only change WHEN batch work runs, never what interactive sees)
    texts_off = [o["choices"][0]["text"] for o in outs_off]
    texts_on = [o["choices"][0]["text"] for o in outs_on]
    assert texts_on == texts_off
    # recovered throughput
    assert job is not None and job["status"] == "done"
    assert job["completed"] == 6
    assert fleet_on.batch.recovered_tokens > 0
    # the front door admitted interactive only (batch bypassed)
    assert fleet_on.admission.admitted == fleet_off.admission.admitted
