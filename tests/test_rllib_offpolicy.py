"""DQN / SAC / offline BC (reference parity: rllib/algorithms/dqn, sac,
bc + offline_data — the off-policy & offline side of RLlib)."""

import numpy as np
import pytest

from ray_tpu.rllib import (BC, DQN, SAC, BCConfig, DQNConfig, ReplayBuffer,
                           SACConfig, record_samples)


def test_replay_buffer_ring_and_sample():
    buf = ReplayBuffer(capacity=100, seed=0)
    for start in range(0, 250, 50):
        buf.add_batch({"x": np.arange(start, start + 50),
                       "y": np.ones(50)})
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["x"].shape == (32,)
    # ring kept only the newest 100 values
    assert s["x"].min() >= 150


def test_dqn_learns_cartpole():
    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                           rollout_fragment_length=64)
              .training(lr=1e-3, buffer_size=20_000, train_batch_size=128,
                        num_updates_per_iter=16,
                        num_steps_before_learning=500,
                        target_network_update_freq=50, epsilon=0.15)
              .debugging(seed=0))
    algo = config.build()
    first = None
    best = -np.inf
    for i in range(30):
        m = algo.step()
        if not np.isnan(m["episode_return_mean"]):
            if first is None:
                first = m["episode_return_mean"]
            best = max(best, m["episode_return_mean"])
    algo.cleanup()
    assert first is not None
    assert best > first + 15, (first, best)


def test_sac_learns_pendulum():
    config = (SACConfig()
              .environment("Pendulum-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                           rollout_fragment_length=64)
              .training(lr=3e-3, buffer_size=50_000, train_batch_size=256,
                        num_updates_per_iter=32,
                        num_steps_before_learning=1_000,
                        action_scale=2.0)
              .debugging(seed=0))
    algo = config.build()
    returns = []
    for i in range(25):
        m = algo.step()
        if not np.isnan(m["episode_return_mean"]):
            returns.append(m["episode_return_mean"])
    algo.cleanup()
    # pendulum returns start ~-1200..-1600; learning shows clear movement up
    assert returns, "no episodes finished"
    assert max(returns[5:]) > returns[0] + 150, returns


def test_bc_from_recorded_samples(tmp_path):
    # record a few rollouts from a PPO-style default policy
    from ray_tpu.rllib import PPOConfig
    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                           rollout_fragment_length=32))
    algo = config.build()
    for i in range(3):
        result = algo.env_runner_group.sample()
        record_samples(result["batch"], str(tmp_path / "data"),
                       shard_index=i)
    algo.cleanup()

    bc_cfg = (BCConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                           rollout_fragment_length=32)
              .offline_data(input_path=str(tmp_path / "data"))
              .training(lr=1e-3, num_updates_per_iter=8))
    bc = bc_cfg.build()
    m1 = bc.step()
    m2 = bc.step()
    bc.cleanup()
    # the BC loss (negative data log-likelihood) must drop
    assert m2["learner/total_loss"] < m1["learner/total_loss"]


def test_tpe_searcher_optimizes(ray_start):
    """TPE beats random given the same budget on a smooth 2-d bowl."""
    from ray_tpu import tune
    from ray_tpu.tune import TPESearch

    def objective(config):
        x, y = config["x"], config["y"]
        tune.report({"loss": (x - 0.3) ** 2 + (y + 0.5) ** 2})

    space = {"x": tune.uniform(-2.0, 2.0), "y": tune.uniform(-2.0, 2.0)}
    tpe = TPESearch(space, metric="loss", mode="min", num_samples=40,
                    n_startup_trials=8, seed=0)
    # max_concurrent_trials=1 pins trial COMPLETION order, which pins the
    # searcher's RNG consumption — without it suite load reorders result
    # arrival and this becomes an unseeded stochastic assertion (flaked
    # ~1-in-N suite runs in round 4).
    result = tune.run(objective, config=space, search_alg=tpe,
                      metric="loss", mode="min", verbose=0,
                      max_concurrent_trials=1)
    best_tpe = result.get_best_result().metrics["loss"]
    # absolute quality on the bowl + model-phase improvement. (Beating
    # random is asserted properly — across seeds — in
    # test_search_regression; a single-seed race here is a coin flip,
    # and the adaptive-Parzen TPE keeps exploring late so late-trial
    # AVERAGES are not the signal either.)
    assert best_tpe < 0.5, best_tpe
    losses = [t.last_result["loss"] for t in result._trials
              if t.last_result and "loss" in t.last_result]
    # Model phase improves on startup OR is already near-optimal: under
    # suite load trial completion order shifts the searcher's RNG
    # consumption, so a lucky startup draw must not flip the test (the
    # proper across-seeds beat-random assertion lives in
    # test_search_regression).
    assert min(losses[8:]) < max(min(losses[:8]), 0.25), losses
