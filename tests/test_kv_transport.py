"""Fleet KV transport (ISSUE 12): wire format, session export/import,
disaggregated prefill/decode, live migration, fleet prefix store.

Gates:
- serialization property test: seeded roundtrip over ragged page
  shapes, partial last pages, and dtype variants (f32/f16/bf16)
  asserts BYTE-identical restore; corrupted/truncated payloads are
  rejected with TransportError/TransportChecksumError (the fleet
  falls back to replay — never a crash);
- THE disaggregation acceptance gate: prefill-on-A / decode-on-B via
  the fleet relay produces token-identical output (greedy AND
  sampled) vs a single-engine oracle;
- live migration: a drain mid-stream ships the session instead of
  replaying; severing the ship (chaos) and corrupting the payload
  both degrade to the PR 9 replay path, still token-exact with
  exactly-once delivery;
- failover-by-restore: a wedged replica whose session was already
  parked hands the pages over instead of forcing a full replay;
- fleet prefix store: a prefix prefilled on one replica seeds the
  next replica's cache (match_prefix hits, output still
  oracle-exact);
- host-tier byte accounting (`kv_host_bytes_used`) across stats,
  fleet_stats, the Prometheus gauge, and the /fleet snapshot row.

Everything here is in-process (LocalReplicaClient over real engines
on CPU) — no cross-process transport tests exist yet; any future ones
must take the `slow` marker so tier-1 stays in-process.
"""

import asyncio
import base64
import json
import uuid

import numpy as np
import jax.numpy as jnp
import pytest

from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)
from ray_tpu.llm._internal.server import LLMServerImpl
from ray_tpu.models import llama
from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                               ChaosReplicaClient, ChaosSchedule,
                               FleetManager, HealthConfig,
                               LocalReplicaClient, RouterConfig,
                               TransportConfig)
from ray_tpu.serve.llm import kv_transport as kvt
from ray_tpu.serve.llm.router import ReplicaSnapshot, prefix_fingerprint

# ---------------------------------------------------------------- helpers

_ENGINE_KW = dict(max_batch_size=4, page_size=8, num_pages=128, seed=7,
                  max_seq_len=1024, prefill_buckets=(16, 32, 64),
                  max_prefill_tokens=32, enable_kv_offload=True)


def _engine(**over):
    kw = dict(model=llama.config("debug", dtype=jnp.float32),
              **_ENGINE_KW)
    kw.update(over)
    return InferenceEngine(EngineConfig(**kw))


def _run(eng, cap=5000):
    steps = 0
    while eng.has_work():
        eng.step()
        steps += 1
        assert steps < cap, "engine failed to converge"


def _make_server(rid, tag):
    return LLMServerImpl({
        "model_id": "m", "model_source": "debug",
        "engine_kwargs": dict(_ENGINE_KW, metrics_model_id=tag,
                              metrics_replica_id=rid),
    })


_state = {}


@pytest.fixture(scope="module")
def transport_servers():
    """Two real engine replicas, WARMED (compiles done — the stall-
    and migration-driven tests use short watchdog timeouts that must
    never race a cold compile)."""
    if "servers" not in _state:
        tag = f"kvt{uuid.uuid4().hex[:8]}"
        servers = {rid: _make_server(rid, tag) for rid in ("r0", "r1")}

        async def warm():
            for s in servers.values():
                await s.completions({"prompt": "warmup " * 8,
                                     "max_tokens": 4})
            _cancel_pumps(servers)
        asyncio.run(warm())
        _state["servers"] = servers
    return _state["servers"]


def _cancel_pumps(servers):
    for srv in servers.values():
        if srv._pump is not None:
            srv._pump.cancel()


def _fleet_over(servers, clients=None, **over):
    kw = dict(
        router=RouterConfig(prefix_depth=64, spill_waiting=16),
        admission=AdmissionConfig(max_concurrent=8, max_queue=16,
                                  queue_wait_slo_s=30.0),
        autoscale=AutoscaleConfig(min_replicas=2, max_replicas=2),
        transport=TransportConfig(enable_disagg=False,
                                  enable_prefix_store=False),
        drain_timeout_s=10.0)
    kw.update(over)
    return FleetManager(
        clients if clients is not None else
        [LocalReplicaClient(rid, srv)
         for rid, srv in servers.items()], **kw)


def _sse_transcript(chunks):
    toks, texts, reasons = [], [], []
    for c in chunks:
        if not c.startswith("data: "):
            continue
        d = c[len("data: "):].strip()
        if d == "[DONE]":
            continue
        ch = json.loads(d)["choices"][0]
        toks += ch.get("token_ids") or []
        texts.append(ch.get("text") or ch.get("delta", {})
                     .get("content") or "")
        if ch.get("finish_reason"):
            reasons.append(ch["finish_reason"])
    assert len(reasons) == 1, f"want exactly one finish: {reasons}"
    return toks, "".join(texts), reasons[0]


def _oracle_tokens(body):
    """Single-engine oracle stream (same weights seed as the fleet
    replicas), by token ids. One oracle engine serves every test —
    engine construction/compiles dominate this file's runtime, and
    greedy/seeded outputs are batch-history-independent."""
    if "oracle" not in _state:
        _state["oracle"] = _make_server("oracle",
                                        f"o{uuid.uuid4().hex[:6]}")
    srv = _state["oracle"]

    async def main():
        out = []
        async for c in srv.completions_stream_tokens(dict(body)):
            out.append(c)
        _cancel_pumps({"o": srv})
        return [t for c in out for t in c["toks"]]

    return asyncio.run(main())


def _drive_stream(fleet, servers, body, on_chunk=None):
    """Consume one fleet SSE stream; on_chunk(n, loop-context) runs
    after each chunk (the mid-stream fault injection hook)."""

    async def main():
        chunks = []
        async for c in fleet.dispatch_stream("completions_stream",
                                             dict(body)):
            chunks.append(c)
            if on_chunk is not None:
                await on_chunk(len(chunks))
        _cancel_pumps(servers)
        return chunks

    return asyncio.run(main())


# ------------------------------------------------- wire-format property

def _random_state(rng, dtype):
    L = int(rng.integers(1, 3))
    n_pages = int(rng.integers(1, 6))
    page = int(rng.choice([4, 8]))
    H = int(rng.integers(1, 3))
    D = int(rng.choice([4, 8]))
    shape = (L, n_pages, page, H, D)
    k = rng.standard_normal(shape).astype(dtype)
    v = rng.standard_normal(shape).astype(dtype)
    prompt = rng.integers(2, 250, int(rng.integers(4, 40))).tolist()
    # partial last page: position deliberately NOT page-aligned
    position = (n_pages - 1) * page + int(rng.integers(1, page + 1))
    return {
        "request_id": f"req-{rng.integers(1 << 30)}",
        "prompt_tokens": prompt,
        "output_tokens": rng.integers(2, 250,
                                      int(rng.integers(0, 8))).tolist(),
        "params": {"max_tokens": int(rng.integers(1, 64)),
                   "temperature": float(rng.random()),
                   "top_p": 0.9, "top_k": 3,
                   "repetition_penalty": 1.1,
                   "stop_token_ids": [0], "seed": 123},
        "lora": None, "priority": int(rng.integers(-2, 3)),
        "restarts": int(rng.integers(0, 3)), "trace": None,
        "deadline_epoch": None,
        "seed": int(rng.integers(1 << 31)),
        "position": position, "last_token": int(rng.integers(2, 250)),
        "n_pages": n_pages, "k": k, "v": v,
    }


def test_wire_session_roundtrip_property():
    """Seeded roundtrip over ragged page shapes, partial last pages,
    and dtype variants: decode(encode(state)) is BYTE-identical — the
    KV arrays bit-for-bit, every metadata field equal."""
    import ml_dtypes
    rng = np.random.default_rng(42)
    dtypes = [np.float32, np.float16, ml_dtypes.bfloat16]
    for trial in range(24):
        state = _random_state(rng, dtypes[trial % len(dtypes)])
        blob = kvt.encode_session(state)
        # the frame is also stable: same state -> same bytes
        assert blob == kvt.encode_session(state)
        out = kvt.decode_session(blob)
        for key in ("request_id", "prompt_tokens", "output_tokens",
                    "params", "lora", "priority", "restarts",
                    "seed", "position", "last_token", "n_pages"):
            assert out[key] == state[key], key
        for name in ("k", "v"):
            assert out[name].dtype == state[name].dtype
            assert out[name].shape == state[name].shape
            assert out[name].tobytes() == state[name].tobytes()
        # b64 transport wrapper is lossless too
        assert kvt.from_b64(kvt.to_b64(blob)) == blob


def test_wire_cold_session_roundtrip():
    rng = np.random.default_rng(7)
    state = _random_state(rng, np.float32)
    state.update(n_pages=0, position=0, last_token=0, k=None, v=None,
                 output_tokens=[])
    out = kvt.decode_session(kvt.encode_session(state))
    assert out["k"] is None and out["v"] is None
    assert out["n_pages"] == 0
    assert out["prompt_tokens"] == state["prompt_tokens"]


def test_wire_prefix_roundtrip():
    rng = np.random.default_rng(9)
    k = rng.standard_normal((2, 3, 8, 2, 4)).astype(np.float32)
    v = rng.standard_normal((2, 3, 8, 2, 4)).astype(np.float32)
    toks = list(range(2, 26))
    pfx = kvt.decode_prefix(kvt.encode_prefix(toks, k, v))
    assert pfx["tokens"] == toks
    assert pfx["k"].tobytes() == k.tobytes()
    assert pfx["v"].tobytes() == v.tobytes()
    # an f32 frame (and any decoded v1 frame) resolves to kind f32
    # with no scale arrays
    assert pfx["kv_dtype"] == "f32"
    assert pfx["k_scales"] is None and pfx["v_scales"] is None


def test_wire_rejects_corruption():
    """Every corrupted byte is caught (crc32 covers the whole frame),
    truncation/magic/version faults raise TransportError — and none
    of them raise anything BUT the transport error family (the
    fleet's fall-back-to-replay contract hangs on that)."""
    rng = np.random.default_rng(3)
    blob = kvt.encode_session(_random_state(rng, np.float32))
    # corrupt one byte at positions spread across header and payload
    for frac in (0.1, 0.3, 0.5, 0.7, 0.95):
        bad = bytearray(blob)
        bad[int(len(bad) * frac)] ^= 0xFF
        with pytest.raises(kvt.TransportError):
            kvt.decode_session(bytes(bad))
    # checksum corruption specifically is the checksum subclass
    bad = bytearray(blob)
    bad[len(bad) // 2] ^= 0xFF
    with pytest.raises(kvt.TransportChecksumError):
        kvt.decode_session(bytes(bad))
    # truncations at every boundary
    for cut in (0, 3, 8, len(blob) // 2, len(blob) - 1):
        with pytest.raises(kvt.TransportError):
            kvt.decode_session(blob[:cut])
    with pytest.raises(kvt.TransportError):
        kvt.decode_session(b"NOPE" + blob[4:])
    with pytest.raises(kvt.TransportError):
        kvt.decode_session(b"not even a frame")
    with pytest.raises(kvt.TransportError):
        kvt.from_b64("!!! not base64 !!!")
    # a prefix frame is not a session frame
    with pytest.raises(kvt.TransportError):
        kvt.decode_session(kvt.encode_prefix(
            [1, 2], np.zeros((1, 1, 2, 1, 2), np.float32),
            np.zeros((1, 1, 2, 1, 2), np.float32)))


def test_wire_rejects_crc_valid_lying_header():
    """A frame whose crc is VALID but whose header lies about its
    arrays (shape inconsistent with nbytes) must still raise
    TransportError, not a bare numpy ValueError — consumers key the
    fall-back-to-replay contract on the transport error family."""
    import struct
    import zlib

    rng = np.random.default_rng(5)
    blob = kvt.encode_session(_random_state(rng, np.float32))
    _, hlen = struct.unpack("<HI", blob[4:10])
    header = json.loads(blob[10:10 + hlen])
    header["arrays"][0]["shape"][0] += 1      # size no longer matches
    new_header = json.dumps(header, sort_keys=True).encode()
    body = (blob[:4]
            + struct.pack("<HI", kvt.WIRE_VERSION, len(new_header))
            + new_header + blob[10 + hlen:-4])
    bad = body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)
    with pytest.raises(kvt.TransportError, match="array"):
        kvt.decode_session(bad)


# ------------------------------------------- engine-level session moves

@pytest.mark.parametrize("sp", [
    {"max_tokens": 24},
    {"max_tokens": 24, "temperature": 0.8, "top_p": 0.9,
     "seed": 4242},
], ids=["greedy", "sampled"])
def test_engine_export_import_token_exact(sp):
    """Session shipped mid-decode A->B continues BYTE-identical to a
    never-moved oracle (restored pages are bit-exact copies; sampling
    keys are fold_in(seed, absolute index)); the wire roundtrip rides
    the real encode/decode path."""
    rng = np.random.default_rng(11)
    prompt = rng.integers(2, 250, 20).tolist()
    ora = _engine()
    o = Request("q0", list(prompt), SamplingParams(**sp))
    ora.add_request(o)
    _run(ora)

    a = _engine()
    r = Request("q0", list(prompt), SamplingParams(**sp))
    a.add_request(r)
    while len(r.output_tokens) < 5:
        a.step()
    state = a.export_session("q0", reason="test")
    assert state is not None
    assert r.finished and r.finish_reason == "migrated"
    assert a.host_tier.exports_total == 1
    assert len(a.host_tier) == 0 and a.host_tier.used_bytes == 0

    b = _engine()
    req = b.import_session(kvt.decode_session(
        kvt.encode_session(state)))
    assert req.output_tokens == state["output_tokens"]
    _run(b)
    assert req.finished and req.finish_reason in ("length", "stop")
    assert o.output_tokens == req.output_tokens
    assert b.host_tier.restores_total == 1
    # A keeps serving after the export
    r2 = Request("after", rng.integers(2, 250, 8).tolist(),
                 SamplingParams(max_tokens=4))
    a.add_request(r2)
    _run(a)
    assert r2.finished


def test_engine_cold_export_from_waiting_queue():
    """A request still in the waiting queue exports COLD (no pages);
    the importer re-admits it and the generation is oracle-exact."""
    rng = np.random.default_rng(13)
    prompts = [rng.integers(2, 250, 12).tolist() for _ in range(2)]
    ora = _engine()
    o = Request("w1", list(prompts[1]), SamplingParams(max_tokens=8))
    ora.add_request(o)
    _run(ora)

    a = _engine(max_batch_size=1)
    a.add_request(Request("w0", list(prompts[0]),
                          SamplingParams(max_tokens=8)))
    a.add_request(Request("w1", list(prompts[1]),
                          SamplingParams(max_tokens=8)))
    state = a.export_session("w1")            # head-of-queue blocked
    assert state is not None and state["n_pages"] == 0
    b = _engine()
    req = b.import_session(state)
    _run(b)
    _run(a)
    assert req.output_tokens == o.output_tokens


def test_engine_import_rejects_bad_sessions():
    rng = np.random.default_rng(17)
    prompt = rng.integers(2, 250, 20).tolist()
    a = _engine()
    r = Request("dup", list(prompt), SamplingParams(max_tokens=24))
    a.add_request(r)
    while len(r.output_tokens) < 3:
        a.step()
    state = a.export_session("dup")
    b = _engine()
    b.import_session({**state})
    # same id already live here -> rejected (the relay replays)
    with pytest.raises(ValueError, match="already live"):
        b.import_session({**state})
    # incompatible geometry -> rejected before touching the pool
    c = _engine()
    bad = dict(state, k=state["k"][:, :, :4], v=state["v"][:, :, :4])
    with pytest.raises(ValueError, match="geometry"):
        c.import_session(bad)
    # inconsistent position/page accounting -> rejected
    bad = dict(state, position=1)
    with pytest.raises(ValueError, match="inconsistent"):
        c.import_session(bad)
    # a cold session that somehow carries emitted tokens must replay
    bad = dict(state, n_pages=0, k=None, v=None)
    with pytest.raises(ValueError, match="replay"):
        c.import_session(bad)
    _run(b)


def test_engine_prefix_export_import_hits_and_is_exact():
    """Prefix pages prefilled on A and imported into B make B's
    match_prefix hit AND leave the generated suffix oracle-exact
    (the imported pages are bit-exact KV for the same weights)."""
    sys_prefix = list(range(2, 2 + 32))       # 4 full pages
    a = _engine()
    ra = Request("p0", sys_prefix + [100, 101, 102],
                 SamplingParams(max_tokens=6))
    a.add_request(ra)
    _run(a)
    exp = a.export_prefix(sys_prefix)
    assert exp is not None and exp["k"].shape[1] == 4
    pfx = kvt.decode_prefix(kvt.encode_prefix(
        exp["tokens"], exp["k"], exp["v"]))
    toks, k, v = pfx["tokens"], pfx["k"], pfx["v"]

    b = _engine()
    assert b.import_prefix(toks, k, v) == 4
    assert b.import_prefix(toks, k, v) == 0   # idempotent
    suffix = [110, 111, 112, 113]
    ora = _engine()
    ro = Request("p1", sys_prefix + suffix,
                 SamplingParams(max_tokens=8))
    ora.add_request(ro)
    _run(ora)
    rb = Request("p1", sys_prefix + suffix,
                 SamplingParams(max_tokens=8))
    b.add_request(rb)
    _run(b)
    assert b.allocator.cache_hit_tokens >= 32
    assert rb.output_tokens == ro.output_tokens


def test_host_tier_byte_accounting_surfaces():
    """ISSUE 12 satellite: `kv_host_bytes_used` is visible in the
    tier stats, engine stats, fleet_stats, the Prometheus gauge, and
    the /fleet snapshot row — and returns to zero when the tier
    empties."""
    rng = np.random.default_rng(19)
    eng = _engine()
    r = Request("b0", rng.integers(2, 250, 20).tolist(),
                SamplingParams(max_tokens=40))
    eng.add_request(r)
    while len(r.output_tokens) < 3:
        eng.step()
    assert eng.preempt("b0", reason="manual")
    tier = eng.host_tier
    parked = tier.entries()[0]
    want = parked.payload_bytes()
    assert want > 0
    assert tier.used_bytes == want
    assert tier.stats()["host_bytes_used"] == want
    assert eng.stats()["host_bytes_used"] == want
    # telemetry gauge renders at scrape time
    eng.telemetry.update_gauges(eng)
    assert "ray_tpu_llm_kv_host_bytes_used" in \
        eng.prometheus_metrics()
    # fleet surface: fleet_stats row -> ReplicaSnapshot -> /fleet row
    srv = LLMServerImpl.__new__(LLMServerImpl)
    srv.engine = eng
    srv.replica_id = "rX"
    srv.model_id = "m"
    stats = srv._fleet_stats_sync()
    assert stats["kv_host_bytes_used"] == want
    snap = ReplicaSnapshot.from_stats(stats)
    assert snap.kv_host_bytes == want
    _run(eng)                                  # restore + finish
    assert tier.used_bytes == 0 and tier.stats()["host_bytes_used"] \
        == 0


# ------------------------------------------------ fleet e2e: disagg

@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
def test_e2e_disagg_prefill_on_a_decode_on_b_token_exact(
        transport_servers, sampled):
    """THE acceptance gate: a long prompt prefills on the `prefill`
    replica, the parked session ships, and the `decode` replica
    resumes it — the client transcript is token-identical to a
    single-engine oracle, greedy AND sampled, with the prefill
    replica kept out of the router ring."""
    gen = 16
    body = {"prompt": "long shared context " * 16, "max_tokens": gen}
    if sampled:
        body.update(temperature=0.8, top_p=0.9, seed=20124)
    fleet = _fleet_over(
        transport_servers,
        roles=["prefill", "decode"],
        transport=TransportConfig(disagg_prompt_chars=64,
                                  enable_prefix_store=False))
    assert fleet.router.ring.nodes() == ["r1"]
    exports0 = transport_servers["r0"].engine.host_tier.exports_total
    restores0 = transport_servers["r1"].engine.host_tier \
        .restores_total
    chunks = _drive_stream(fleet, transport_servers, body)
    toks, _, reason = _sse_transcript(chunks)
    assert reason in ("length", "stop")
    want = _oracle_tokens(body)
    assert len(want) == gen
    assert toks == want, "disaggregated transcript diverged"
    # the ship REALLY happened: prefill exported, decode restored
    assert transport_servers["r0"].engine.host_tier.exports_total \
        == exports0 + 1
    assert transport_servers["r1"].engine.host_tier.restores_total \
        == restores0 + 1
    evs = [e["event"] for e in fleet.recorder.events()]
    assert "disagg_handoff" in evs
    # transport spans land in the ingress trace buffer
    names = {e.get("name") for e in fleet.trace.events()}
    assert "disagg_prefill" in names


def test_e2e_disagg_short_prompt_skips_handoff(transport_servers):
    """Prompts under the threshold take the normal decode-replica
    path — no ship, no prefill-replica involvement."""
    fleet = _fleet_over(
        transport_servers,
        roles=["prefill", "decode"],
        transport=TransportConfig(disagg_prompt_chars=256,
                                  enable_prefix_store=False))
    exports0 = transport_servers["r0"].engine.host_tier.exports_total
    body = {"prompt": "short", "max_tokens": 4}
    chunks = _drive_stream(fleet, transport_servers, body)
    toks, _, reason = _sse_transcript(chunks)
    assert reason in ("length", "stop") and len(toks) == 4
    assert transport_servers["r0"].engine.host_tier.exports_total \
        == exports0
    assert "disagg_handoff" not in [
        e["event"] for e in fleet.recorder.events()]


def test_e2e_disagg_prefill_failure_falls_back(transport_servers):
    """A dead prefill replica degrades to mixed prefill on the
    decode replica — same tokens, one failed-handoff breadcrumb."""
    schedules = {rid: ChaosSchedule() for rid in transport_servers}
    schedules["r0"].fail_calls(method="prefill_export", count=-1)
    clients = [ChaosReplicaClient(
        LocalReplicaClient(rid, srv), schedules[rid])
        for rid, srv in transport_servers.items()]
    fleet = _fleet_over(
        transport_servers, clients=clients,
        roles=["prefill", "decode"],
        transport=TransportConfig(disagg_prompt_chars=64,
                                  enable_prefix_store=False))
    body = {"prompt": "fall back to mixed prefill " * 8,
            "max_tokens": 8}
    chunks = _drive_stream(fleet, transport_servers, body)
    toks, _, reason = _sse_transcript(chunks)
    assert toks == _oracle_tokens(body)
    assert [f["kind"] for f in schedules["r0"].fired] \
        == ["call_error"]
    assert "disagg_fallback" in [
        e["event"] for e in fleet.recorder.events()]


# ------------------------------------------ fleet e2e: live migration

def test_e2e_drain_migration_ships_session_token_exact(
        transport_servers):
    """Drain-before-downscale mid-stream: the victim's live session
    ships to the survivor (pages, not token replay), the stream
    completes token-exact with exactly-once delivery, and the victim
    parks on STANDBY."""
    gen = 400
    body = {"prompt": "drain migration scenario prompt",
            "max_tokens": gen}
    want = _oracle_tokens(body)
    assert len(want) == gen
    fleet = _fleet_over(transport_servers)

    async def main():
        chunks = []
        victim = None
        async for c in fleet.dispatch_stream("completions_stream",
                                             dict(body)):
            chunks.append(c)
            if len(chunks) == 3:
                srid, info = next(iter(fleet._live_streams.items()))
                victim = info["replica"]
                fleet._begin_drain(victim)
            await asyncio.sleep(0)
        # settle on the SAME loop the drain task runs on
        drained = False
        for _ in range(500):
            if fleet.replicas[victim].status == "STANDBY":
                drained = True
                break
            await asyncio.sleep(0.02)
        _cancel_pumps(transport_servers)
        return chunks, victim, drained

    chunks, victim, drained = asyncio.run(main())
    toks, _, reason = _sse_transcript(chunks)
    assert reason == "length"
    assert toks == want, "migrated transcript diverged"
    assert len(toks) == gen                  # exactly-once
    evs = [e["event"] for e in fleet.recorder.events()]
    assert "session_migrated" in evs
    names = {e.get("name") for e in fleet.trace.events()}
    assert "session_migration" in names
    assert drained, "victim never finished draining"


def test_e2e_migration_severed_mid_ship_replays_token_exact(
        transport_servers):
    """THE chaos acceptance gate: the victim's stream is severed
    mid-flight AND its export path is dead (the ship is severed
    mid-migration) — the fleet falls back to PR 9 token replay and
    the client transcript is STILL token-exact with exactly-once
    delivery."""
    gen = 14
    body = {"prompt": "sever the ship mid migration",
            "max_tokens": gen, "temperature": 0.8, "top_p": 0.9,
            "seed": 777}
    want = _oracle_tokens(body)
    fleet0 = _fleet_over(transport_servers)
    fp = prefix_fingerprint(body, 64)
    victim = fleet0.router.pick(fp, {}, {})
    schedules = {rid: ChaosSchedule() for rid in transport_servers}
    schedules[victim].sever_stream(after_chunks=2)
    schedules[victim].fail_calls(method="export_session", count=-1)
    clients = [ChaosReplicaClient(
        LocalReplicaClient(rid, srv), schedules[rid])
        for rid, srv in transport_servers.items()]
    fleet = _fleet_over(transport_servers, clients=clients)
    chunks = _drive_stream(fleet, transport_servers, body)
    toks, _, reason = _sse_transcript(chunks)
    assert reason in ("length", "stop")
    assert toks == want and len(toks) == gen
    kinds = [f["kind"] for f in schedules[victim].fired]
    assert "stream_sever" in kinds and "call_error" in kinds
    evs = [e["event"] for e in fleet.recorder.events()]
    assert "failover" in evs
    assert "failover_restore" not in evs     # the restore path failed


class _CorruptingClient:
    """Flips one payload byte in every export_session response — the
    ship completes but the cargo is damaged (checksum catches it on
    the importing side)."""

    def __init__(self, inner):
        self.inner = inner
        self.replica_id = inner.replica_id

    @property
    def shares_registry(self):
        return bool(getattr(self.inner, "shares_registry", False))

    async def call(self, method, *args):
        out = await self.inner.call(method, *args)
        if method == "export_session" and isinstance(out, dict) \
                and out.get("session"):
            blob = bytearray(base64.b64decode(out["session"]))
            blob[len(blob) // 2] ^= 0xFF
            out = dict(out, session=base64.b64encode(
                bytes(blob)).decode("ascii"))
        return out

    def stream(self, method, body):
        return self.inner.stream(method, body)


def test_e2e_corrupted_ship_falls_back_to_replay(transport_servers):
    """A drain migration whose payload is corrupted in flight: the
    importing replica rejects it (checksum) and the relay degrades
    to token replay — token-exact, pump alive, no crash."""
    gen = 400
    body = {"prompt": "corrupted cargo scenario", "max_tokens": gen}
    want = _oracle_tokens(body)
    clients = [_CorruptingClient(LocalReplicaClient(rid, srv))
               for rid, srv in transport_servers.items()]
    fleet = _fleet_over(transport_servers, clients=clients)
    st = {"victim": None}

    async def on_chunk(n):
        if n == 3:
            srid, info = next(iter(fleet._live_streams.items()))
            st["victim"] = info["replica"]
            fleet._begin_drain(st["victim"])
        await asyncio.sleep(0)

    chunks = _drive_stream(fleet, transport_servers, body, on_chunk)
    toks, _, reason = _sse_transcript(chunks)
    assert reason == "length"
    assert toks == want and len(toks) == gen
    evs = [e["event"] for e in fleet.recorder.events()]
    assert "session_migrated" in evs         # the ship left the dock
    assert "kv_resume_failed" in evs         # ... and was rejected
    # both replicas still serve after the storm
    fleet2 = _fleet_over(transport_servers)

    async def after():
        out = await fleet2.dispatch(
            "completions", {"prompt": "after the storm",
                            "max_tokens": 2})
        _cancel_pumps(transport_servers)
        return out
    assert asyncio.run(after())["choices"][0]["finish_reason"]


def test_e2e_failover_by_restore_wedged_replica(transport_servers):
    """Failover-by-restore (ISSUE 12b): the serving replica WEDGES
    (pump dead) with the session already parked in its host tier.
    The stall watchdog fires and the fleet exports the parked pages
    off the wedged replica instead of replaying the whole transcript
    — resumed on the survivor, token-exact."""
    gen = 400
    body = {"prompt": "wedged replica restore scenario",
            "max_tokens": gen}
    want = _oracle_tokens(body)
    fleet = _fleet_over(
        transport_servers,
        health=HealthConfig(stream_stall_timeout_s=1.5))
    st = {"victim": None, "parked": None}

    async def on_chunk(n):
        if n == 1:
            srid, info = next(iter(fleet._live_streams.items()))
            st["victim"] = info["replica"]
            transport_servers[st["victim"]]._pump.cancel()
            st["parked"] = await asyncio.get_running_loop() \
                .run_in_executor(
                    None, transport_servers[st["victim"]]
                    .engine.preempt, srid)

    chunks = _drive_stream(fleet, transport_servers, body, on_chunk)
    toks, _, reason = _sse_transcript(chunks)
    assert st["parked"], "victim failed to park the session"
    assert reason == "length"
    assert toks == want and len(toks) == gen
    evs = [e["event"] for e in fleet.recorder.events()]
    assert "failover_restore" in evs
    victim_eng = transport_servers[st["victim"]].engine
    assert victim_eng.host_tier.exports_total >= 1
    names = {e.get("name") for e in fleet.trace.events()}
    assert "failover_restore" in names


# -------------------------------------------- fleet e2e: prefix store

def test_e2e_prefix_store_seeds_second_replica(transport_servers):
    """ISSUE 12c: a system prompt prefilled on r0 is published into
    the fleet store and seeded into r1 BEFORE r1's first request of
    that prefix — r1's local prefix cache hits as if it had
    prefilled the prompt itself, and the output stays oracle-exact."""
    sys_prompt = (f"shared system prompt {uuid.uuid4().hex[:8]} "
                  + "s" * 64)[:64]
    fleet = _fleet_over(
        transport_servers,
        router=RouterConfig(policy="round_robin", prefix_depth=64),
        transport=TransportConfig(enable_disagg=False,
                                  prefix_min_chars=64))
    hit0 = {rid: srv.engine.allocator.cache_hit_tokens
            for rid, srv in transport_servers.items()}

    bodies = [{"prompt": sys_prompt + f" user turn {i}",
               "max_tokens": 6} for i in range(2)]
    oracles = [_oracle_tokens(b) for b in bodies]

    async def main():
        outs = []
        for b in bodies:                      # SEQUENTIAL: publish
            outs.append(await fleet.dispatch("completions", dict(b)))
        _cancel_pumps(transport_servers)
        return outs

    outs = asyncio.run(main())
    # round-robin put one request on each replica; the second
    # replica imported the store entry and HIT
    store = fleet.prefix_store
    assert store is not None
    assert store.stats()["publishes"] == 1
    assert store.stats()["hits"] == 1
    hits = sum(v for _, v in
               fleet.kvt_metrics["prefix_store_hits"]._samples())
    assert hits >= 1
    deltas = {rid: srv.engine.allocator.cache_hit_tokens - hit0[rid]
              for rid, srv in transport_servers.items()}
    # both replicas hit the shared prefix: the publisher via its own
    # cache is irrelevant (first request is cold), the OTHER replica
    # via the imported store entry — 64 shared chars = 64 byte
    # tokens = 8 full pages
    assert sum(1 for d in deltas.values() if d >= 64) >= 1, deltas
    evs = [e["event"] for e in fleet.recorder.events()]
    assert "prefix_published" in evs and "prefix_seeded" in evs
    # correctness: store-seeded pages produce oracle-exact output
    tok = transport_servers["r0"].tokenizer
    for out, want, b in zip(outs, oracles, bodies):
        got = out["choices"][0]["text"]
        assert got == tok.decode(want), b["prompt"][-12:]


def test_e2e_transport_status_surface(transport_servers):
    """GET /fleet carries the transport block: roles, prefix-store
    stats, live-stream/migration counts, per-replica role rows."""
    fleet = _fleet_over(
        transport_servers,
        roles=["prefill", "decode"],
        transport=TransportConfig())

    async def main():
        await fleet.refresh()
        return await fleet.status()

    doc = asyncio.run(main())
    assert doc["transport"]["enabled"]
    assert doc["transport"]["roles"] == {"r0": "prefill",
                                         "r1": "decode"}
    assert doc["transport"]["prefix_store"] is not None
    assert doc["replicas"]["r0"]["role"] == "prefill"
    assert "kv_host_bytes_used" in doc["replicas"]["r0"]
    # a transport-less fleet advertises it off
    plain = _fleet_over(transport_servers, transport=None)
    doc2 = asyncio.run(plain.status())
    assert doc2["transport"] == {"enabled": False}


def test_prefix_store_hot_small_outlives_cold_large():
    """ISSUE 13 satellite (ROADMAP item 2 "REMAINS"): eviction is
    hit-frequency-weighted, not LRU-by-bytes — a HOT small prefix
    (the shared system prompt the store exists for) must survive byte
    pressure that evicts a COLD large one, even when the large one
    arrived later (pure LRU would evict the hot entry here)."""
    from ray_tpu.serve.llm.kv_transport import FleetPrefixStore

    store = FleetPrefixStore(capacity_bytes=1000)
    assert store.put("hot", "h" * 100, tokens=8, publisher="r0")
    for _ in range(5):
        assert store.get("hot") is not None      # it earns residency
    # a cold large entry lands AFTER the hot one (more recent under
    # LRU) and fills most of the store
    assert store.put("cold", "c" * 800, tokens=64, publisher="r0")
    # byte pressure: the next put must evict — the victim is the
    # cold large entry (0 hits), NOT the older-but-hot small one
    assert store.put("new", "n" * 500, tokens=32, publisher="r1")
    assert "hot" in store
    assert "cold" not in store
    assert store.evictions == 1
    assert store.stats()["policy"] == "hit-frequency-weighted"
    # repeated pressure: the fresh entry (0 hits) goes before hot
    assert store.put("new2", "m" * 500, tokens=32, publisher="r1")
    assert "hot" in store and "new" not in store


def test_prefix_store_frequency_ties_break_lru():
    """Among equally-cold entries the LEAST recently used evicts
    first (recency is the score's tie-break)."""
    from ray_tpu.serve.llm.kv_transport import FleetPrefixStore

    store = FleetPrefixStore(capacity_bytes=300)
    store.put("a", "a" * 100, tokens=8, publisher="r0")
    store.put("b", "b" * 100, tokens=8, publisher="r0")
    store.put("c", "c" * 100, tokens=8, publisher="r0")
    store.get("a")                    # a is now most recent AND hot
    store.get("b")
    store.get("b")                    # b hotter than a; c coldest
    store.put("d", "d" * 100, tokens=8, publisher="r0")
    assert "c" not in store           # 0 hits: out first
    assert {"a", "b", "d"} <= {k for k in ("a", "b", "d")
                               if k in store}
    store.put("e", "e" * 100, tokens=8, publisher="r0")
    assert "d" not in store           # 0 hits, least recent of those
    assert "a" in store and "b" in store


def test_fleet_config_wire_carries_transport_and_roles():
    """FleetConfig -> to_wire -> ingress-side reconstruction keeps
    the transport policy and the role map (the deployment path's
    JSON hop must not drop ISSUE 12 config)."""
    import types

    from ray_tpu.serve.llm.deployment import FleetConfig

    cfg = FleetConfig(
        llm_config=types.SimpleNamespace(model_id="m"),
        min_replicas=2, max_replicas=2,
        transport=TransportConfig(disagg_prompt_chars=99,
                                  prefix_min_chars=17),
        replica_roles=["prefill", "decode"])
    wire = json.loads(json.dumps(cfg.to_wire()))
    assert wire["replica_roles"] == ["prefill", "decode"]
    back = TransportConfig(**wire["transport"])
    assert back.disagg_prompt_chars == 99
    assert back.prefix_min_chars == 17
    # transport=None stays None on the wire (fleet behaves pre-12)
    off = FleetConfig(llm_config=types.SimpleNamespace(model_id="m"))
    assert off.to_wire()["transport"] is None
    assert off.to_wire()["replica_roles"] is None


def test_fleet_rejects_bad_role_configs(transport_servers):
    with pytest.raises(ValueError, match="decode-capable"):
        _fleet_over(transport_servers,
                    roles=["prefill", "prefill"])
    with pytest.raises(ValueError, match="align"):
        _fleet_over(transport_servers, roles=["mixed"])
    with pytest.raises(ValueError, match="unknown replica roles"):
        _fleet_over(transport_servers, roles=["mixed", "verifier"])


class _FakeRoleClient:
    """Bare client for role-policy unit tests (no engine)."""

    shares_registry = True

    def __init__(self, rid):
        self.replica_id = rid

    async def call(self, method, *args):
        return {}

    def stream(self, method, body):
        raise NotImplementedError


def _role_fleet(roles, min_replicas):
    clients = [_FakeRoleClient(f"r{i}") for i in range(len(roles))]
    return FleetManager(
        clients, roles=roles,
        autoscale=AutoscaleConfig(min_replicas=min_replicas,
                                  max_replicas=len(roles)),
        transport=TransportConfig())


def test_role_aware_lifecycle_never_empties_the_ring():
    """Role-blindness regressions: (a) an initial ACTIVE head that is
    all prefill is rejected at construction; (b) evicting the last
    ring replica never installs a prefill-role standby as the
    replacement (deferred instead); (c) scale-down never drains the
    last decode-capable replica while prefill replicas stay ACTIVE."""
    # (a) first min_replicas all prefill -> loud config error
    with pytest.raises(ValueError, match="min_replicas"):
        _role_fleet(["prefill", "mixed"], min_replicas=1)
    # (b1) only a prefill standby exists: the eviction DEFERS
    fleet = _role_fleet(["mixed", "prefill", "prefill"],
                        min_replicas=2)
    fleet._evict("r0", "test")
    assert fleet.replicas["r0"].status == "ACTIVE"   # deferred
    assert fleet._ring_ids() == ["r0"]
    assert "eviction_deferred" in [
        e["event"] for e in fleet.recorder.events()]
    # (b2) a decode-capable standby exists: it takes over the ring
    fleet = _role_fleet(["mixed", "prefill", "mixed"],
                        min_replicas=2)
    fleet._evict("r0", "test")
    assert fleet.replicas["r0"].status == "UNHEALTHY"
    assert fleet.replicas["r2"].status == "ACTIVE"
    assert fleet._ring_ids() == ["r2"]
    # (c) downscale drains the prefill replica, not the sole ring one
    fleet = _role_fleet(["mixed", "prefill"], min_replicas=2)

    async def downscale():
        fleet._apply_target(1)
        st = {rid: s.status for rid, s in fleet.replicas.items()}
        for s in fleet.replicas.values():
            if s.drain_task is not None:
                s.drain_task.cancel()
        return st

    statuses = asyncio.run(downscale())
    assert statuses["r0"] == "ACTIVE"
    assert statuses["r1"] == "DRAINING"
    assert fleet._ring_ids() == ["r0"]
