"""Unified ragged prefill+decode step (ISSUE 1 / Ragged Paged
Attention, PAPERS.md) and its Pallas kernel (ISSUE 2).

Gates:
- the ragged paged op matches its CPU-exact dense oracle across ragged
  shapes (pure decode, pure prefill, mixed, single-token prompts,
  page-boundary-straddling chunks, padding rows);
- the Pallas ragged kernel (interpret mode — the same program compiles
  on TPU) matches the oracle across GQA group widths, partial last
  pages, decode-only rows, all-padding rows, and start=0 slots;
- the unified engine step is token-exact vs the legacy two-dispatch
  path at temperature 0 (with and without repetition penalty), and
  with decode_impl=pallas_interpret vs the gather path;
- a mixed prefill+decode workload costs exactly ONE compiled dispatch
  per engine tick, and a steady-state decode run holds the jit-cache
  compile counter flat (no bucket-churn recompile storms).
"""

import zlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models import llama
from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                          Request, SamplingParams)
from ray_tpu.ops.ragged_paged_attention import (
    ragged_attention_dense_oracle, ragged_paged_attention_pallas,
    ragged_paged_prefill_decode_attention)


# ------------------------------------------------------------ op vs oracle

def _ragged_case(rng, segs, page_size=4, kvh=2, group=2, d=8, pad=0):
    """Build a ragged batch from [(start, n_tokens)] per slot, scatter
    each slot's context into a paged pool, and return everything both
    the op and the oracle need."""
    b = len(segs)
    h = kvh * group
    max_ctx = max((s for s, _ in segs), default=0)
    max_pages = max(-(-max(s + n for s, n in segs) // page_size), 1)
    num_pages = b * max_pages + 1
    k_pages = np.zeros((num_pages, page_size, kvh, d), np.float32)
    v_pages = np.zeros((num_pages, page_size, kvh, d), np.float32)
    tables = np.arange(b * max_pages, dtype=np.int32).reshape(b, max_pages)
    dense_k = rng.normal(size=(b, max(max_ctx, 1), kvh, d)).astype(
        np.float32)
    dense_v = rng.normal(size=(b, max(max_ctx, 1), kvh, d)).astype(
        np.float32)
    for s in range(b):
        for p in range(segs[s][0]):
            page = tables[s, p // page_size]
            k_pages[page, p % page_size] = dense_k[s, p]
            v_pages[page, p % page_size] = dense_v[s, p]
    t = sum(n for _, n in segs) + pad
    slot_ids = np.zeros(t, np.int32)
    positions = np.zeros(t, np.int32)
    valid = np.zeros(t, bool)
    cur = 0
    for s, (start, n) in enumerate(segs):
        slot_ids[cur:cur + n] = s
        positions[cur:cur + n] = np.arange(start, start + n)
        valid[cur:cur + n] = True
        cur += n
    q = rng.normal(size=(t, h, d)).astype(np.float32)
    k_new = rng.normal(size=(t, kvh, d)).astype(np.float32)
    v_new = rng.normal(size=(t, kvh, d)).astype(np.float32)
    start = np.asarray([s for s, _ in segs], np.int32)
    return dict(q=q, k_pages=k_pages, v_pages=v_pages, tables=tables,
                slot_ids=slot_ids, positions=positions, valid=valid,
                start=start, k_new=k_new, v_new=v_new,
                dense_k=dense_k, dense_v=dense_v)


@pytest.mark.parametrize("name,segs,pad", [
    ("pure_decode", [(5, 1), (11, 1), (3, 1)], 0),
    ("pure_prefill", [(0, 6), (0, 3), (0, 9)], 0),
    ("mixed", [(7, 1), (0, 5), (12, 1), (4, 6)], 0),
    ("single_token_prompts", [(0, 1), (0, 1), (9, 1)], 0),
    # chunks whose (start, start+n) straddle page boundaries (page=4)
    ("page_straddle", [(3, 6), (6, 5), (2, 1)], 0),
    ("padding_rows", [(5, 1), (0, 4)], 7),
])
def test_ragged_op_matches_dense_oracle(name, segs, pad):
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    c = _ragged_case(rng, segs, pad=pad)
    out = np.asarray(ragged_paged_prefill_decode_attention(
        jnp.asarray(c["q"]), jnp.asarray(c["k_pages"]),
        jnp.asarray(c["v_pages"]), jnp.asarray(c["tables"]),
        jnp.asarray(c["slot_ids"]), jnp.asarray(c["positions"]),
        jnp.asarray(c["valid"]), jnp.asarray(c["start"]),
        jnp.asarray(c["k_new"]), jnp.asarray(c["v_new"])))
    ref = ragged_attention_dense_oracle(
        c["q"], c["dense_k"], c["dense_v"], c["k_new"], c["v_new"],
        c["slot_ids"], c["positions"], c["valid"], c["start"])
    np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                               rtol=2e-4, atol=2e-5)


# ------------------------------------------------ pallas kernel vs oracle

def _kernel_out(c, **kw):
    kw.setdefault("q_block", 4)
    kw.setdefault("pages_per_block", 2)
    return np.asarray(ragged_paged_attention_pallas(
        jnp.asarray(c["q"]), jnp.asarray(c["k_pages"]),
        jnp.asarray(c["v_pages"]), jnp.asarray(c["tables"]),
        jnp.asarray(c["slot_ids"]), jnp.asarray(c["positions"]),
        jnp.asarray(c["valid"]), jnp.asarray(c["start"]),
        jnp.asarray(c["k_new"]), jnp.asarray(c["v_new"]), **kw))


def _oracle_out(c):
    return ragged_attention_dense_oracle(
        c["q"], c["dense_k"], c["dense_v"], c["k_new"], c["v_new"],
        c["slot_ids"], c["positions"], c["valid"], c["start"])


@pytest.mark.parametrize("name,segs,pad,kvh,group", [
    # every row decodes (1 token each, ragged contexts)
    ("decode_only", [(5, 1), (11, 1), (3, 1), (8, 1)], 0, 2, 2),
    ("mixed", [(7, 1), (0, 5), (12, 1), (4, 6)], 0, 2, 2),
    # GQA head-group sweep: 1 query head per kv head and a wide group
    ("gqa_group1", [(6, 2), (0, 3), (10, 1)], 0, 3, 1),
    ("gqa_group4", [(6, 2), (0, 3), (10, 1)], 0, 2, 4),
    # contexts ending mid-page (page_size=4): the kernel must mask the
    # tail of the last streamed page
    ("partial_last_page", [(5, 3), (9, 1), (1, 2), (6, 1)], 0, 2, 2),
    # fresh slots: no cached context, in-batch causal only
    ("start_zero", [(0, 1), (0, 4), (0, 1)], 0, 2, 2),
    ("padding_rows", [(5, 1), (0, 4)], 7, 2, 2),
    # a slot with zero tokens this tick + nothing but padding rows
    ("all_padding", [(0, 0)], 6, 2, 2),
])
def test_pallas_ragged_kernel_matches_oracle(name, segs, pad, kvh, group):
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    c = _ragged_case(rng, segs, pad=pad, kvh=kvh, group=group)
    out = _kernel_out(c, interpret=True)
    ref = _oracle_out(c)
    np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                               rtol=2e-3, atol=2e-3)
    # invalid rows must come back exact zeros (finite downstream)
    if (~c["valid"]).any():
        assert np.all(out[~c["valid"]] == 0.0)


def test_pallas_ragged_kernel_ctx_and_seg_bounds():
    """The static bounds (ctx_pages sweep cut, max_seg_len staging cut)
    must not change the math when they cover the live data."""
    rng = np.random.default_rng(11)
    c = _ragged_case(rng, [(6, 1), (0, 3), (5, 4)])
    full = _kernel_out(c, interpret=True)
    bounded = _kernel_out(c, interpret=True, ctx_pages=2, max_seg_len=4)
    np.testing.assert_allclose(full[c["valid"]], bounded[c["valid"]],
                               rtol=1e-5, atol=1e-6)


def test_pallas_ragged_kernel_block_size_invariance():
    """Online softmax must be exact under any blocking: q_block and
    pages_per_block sweeps agree with each other and the oracle."""
    rng = np.random.default_rng(12)
    c = _ragged_case(rng, [(7, 1), (0, 5), (12, 1), (4, 6)])
    ref = _oracle_out(c)
    for q_blk, ppb in [(1, 1), (2, 4), (8, 3)]:
        out = _kernel_out(c, interpret=True, q_block=q_blk,
                          pages_per_block=ppb)
        np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                                   rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_pallas_ragged_kernel_compiled_tpu():
    """Compiled-kernel equivalence — needs real TPU hardware (the
    interpret-mode gates above cover CPU CI)."""
    if jax.devices()[0].platform == "cpu":
        pytest.skip("compiled Pallas kernel requires a TPU")
    rng = np.random.default_rng(13)
    c = _ragged_case(rng, [(37, 1), (0, 24), (130, 1), (65, 9)],
                     page_size=16, kvh=4, group=2, d=128)
    out = _kernel_out(c, interpret=False, q_block=8, pages_per_block=4)
    ref = _oracle_out(c)
    np.testing.assert_allclose(out[c["valid"]], ref[c["valid"]],
                               rtol=2e-3, atol=2e-3)


def test_ragged_op_ctx_bucketing_matches_full_table():
    """ctx_pages bounds the gather to the pages that exist — same
    output as gathering the whole table."""
    rng = np.random.default_rng(0)
    c = _ragged_case(rng, [(6, 1), (0, 3), (5, 4)])
    args = (jnp.asarray(c["q"]), jnp.asarray(c["k_pages"]),
            jnp.asarray(c["v_pages"]), jnp.asarray(c["tables"]),
            jnp.asarray(c["slot_ids"]), jnp.asarray(c["positions"]),
            jnp.asarray(c["valid"]), jnp.asarray(c["start"]),
            jnp.asarray(c["k_new"]), jnp.asarray(c["v_new"]))
    full = np.asarray(ragged_paged_prefill_decode_attention(*args))
    bucketed = np.asarray(ragged_paged_prefill_decode_attention(
        *args, ctx_pages=2))            # 2 pages cover start=6
    np.testing.assert_allclose(full[c["valid"]], bucketed[c["valid"]],
                               rtol=1e-6, atol=1e-7)


# --------------------------------------------- unified vs legacy engines

def _engine(unified, **over):
    kw = dict(model=llama.config("debug", dtype=jnp.float32),
              max_batch_size=3, page_size=8, num_pages=64,
              prefill_buckets=(16, 32, 64), max_prefill_tokens=16,
              seed=9, unified_step=unified)
    kw.update(over)
    return InferenceEngine(EngineConfig(**kw))


def _drive(eng, prompts, **sp):
    """Staggered mixed workload: more requests than slots, added while
    earlier ones decode — every tick mixes prefill chunks and decode."""
    reqs = [Request(f"r{i}", list(p), SamplingParams(**sp))
            for i, p in enumerate(prompts)]
    for r in reqs[:2]:
        eng.add_request(r)
    for r in reqs[2:]:
        eng.step()
        eng.add_request(r)
    while eng.has_work():
        eng.step()
    return [r.output_tokens for r in reqs]


def _prompts():
    rng = np.random.default_rng(3)
    # longer than the 16-token chunk (chunked prefill), plus short and
    # single-token prompts
    lens = (40, 23, 1, 33, 7, 19)
    return [rng.integers(2, 250, n).tolist() for n in lens]


def test_unified_step_token_exact_vs_legacy_greedy():
    out_u = _drive(_engine(True), _prompts(), max_tokens=12)
    out_l = _drive(_engine(False), _prompts(), max_tokens=12)
    assert out_u == out_l


def test_unified_step_token_exact_with_repetition_penalty():
    """Greedy + CTRL penalty: the seen bookkeeping of the ragged step
    (chunk tokens before sampling, emitted samples after) must
    reproduce the legacy prior/seen handling exactly."""
    out_u = _drive(_engine(True), _prompts(), max_tokens=10,
                   repetition_penalty=1.3)
    out_l = _drive(_engine(False), _prompts(), max_tokens=10,
                   repetition_penalty=1.3)
    assert out_u == out_l


def test_unified_step_composes_with_prefix_cache():
    rng = np.random.default_rng(5)
    shared = rng.integers(2, 250, 24).tolist()
    prompts = [shared + [5], shared + [9, 11]]
    eng = _engine(True, enable_prefix_caching=True)
    outs = [eng.generate([list(p)], SamplingParams(max_tokens=8)
                         )[0].output_tokens for p in prompts]
    assert eng.allocator.cache_hit_tokens >= 16
    cold = _engine(False, enable_prefix_caching=False)
    ref = [cold.generate([list(p)], SamplingParams(max_tokens=8)
                         )[0].output_tokens for p in prompts]
    assert outs == ref


def test_unified_step_one_dispatch_per_tick():
    """The tentpole contract: a mixed prefill+decode workload costs
    exactly ONE compiled dispatch per engine tick (the legacy path
    pays two on every mixed tick, more when draining a cold batch)."""
    eng = _engine(True)
    for i, p in enumerate(_prompts()):
        eng.add_request(Request(f"d{i}", list(p),
                                SamplingParams(max_tokens=8)))
    steps = 0
    d0 = eng.dispatches
    while eng.has_work():
        eng.step()
        steps += 1
    assert steps > 0
    assert eng.dispatches - d0 == steps
    assert eng.stats()["dispatches_per_step"] == 1.0

    legacy = _engine(False)
    for i, p in enumerate(_prompts()):
        legacy.add_request(Request(f"l{i}", list(p),
                                   SamplingParams(max_tokens=8)))
    l_steps = 0
    l0 = legacy.dispatches
    while legacy.has_work():
        legacy.step()
        l_steps += 1
    assert legacy.dispatches - l0 > l_steps   # the two-dispatch tick


def test_unified_step_pallas_interpret_token_exact():
    """decode_impl=pallas_interpret routes the ragged tick through the
    Pallas ragged kernel AND the pure-decode tick through the paged
    decode kernel (interpret mode): greedy output must be token-exact
    vs the dense gather engine on a mixed staggered workload."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(2, 250, n).tolist() for n in (40, 23, 1, 19)]
    out_g = _drive(_engine(True, decode_impl="gather"),
                   [list(p) for p in prompts], max_tokens=6)
    out_p = _drive(_engine(True, decode_impl="pallas_interpret"),
                   [list(p) for p in prompts], max_tokens=6)
    assert out_g == out_p


def test_jit_cache_counter_stable_in_steady_state():
    """Engine.stats() exposes the live jit-cache buckets and a
    cumulative compile counter; once a decode batch reaches steady
    state, further ticks must not build new programs (bucket churn
    would show up as a recompile storm here)."""
    eng = _engine(True)
    rng = np.random.default_rng(7)
    for i in range(3):
        eng.add_request(Request(
            f"c{i}", rng.integers(2, 250, 12).tolist(),
            SamplingParams(max_tokens=30)))
    while any(s.request is not None and not s.ready
              for s in eng.slots) or eng.waiting:
        eng.step()
    for _ in range(3):                    # settle the decode loop
        eng.step()
    st0 = eng.stats()["jit_cache"]
    assert st0["compiled_programs"] > 0
    assert st0["ragged_buckets"] == len(eng._ragged_fns)
    for _ in range(12):                   # steady-state decode
        eng.step()
    st1 = eng.stats()["jit_cache"]
    assert st1["compiled_programs"] == st0["compiled_programs"]
    assert st1["ragged_buckets"] == st0["ragged_buckets"]


def test_unified_step_multi_lora_mixed_batch():
    """Per-token adapter indices: a batch mixing base and a strong
    adapter through the ragged step reproduces each request's solo
    output (same gate as the legacy multi-LoRA test)."""
    cfg = llama.config("debug", dtype=jnp.float32)
    eng = _engine(True, model=cfg, max_batch_size=4)
    L, h, q_dim, r = cfg.n_layers, cfg.hidden, cfg.q_dim, 4
    rng = np.random.default_rng(1)
    eng.register_lora("strong", {
        "wq": (rng.normal(0, 0.5, (L, h, r)),
               rng.normal(0, 0.5, (r, q_dim)) * np.ones((L, 1, 1)))})
    prompt = list(rng.integers(2, 250, 20))   # > chunk: ragged ticks
    sp = SamplingParams(max_tokens=6)

    def solo(lora, rid):
        req = Request(rid, list(prompt), sp, lora=lora)
        eng.add_request(req)
        while not req.finished:
            eng.step()
        return req.output_tokens

    base, strong = solo(None, "b"), solo("strong", "s")
    assert base != strong
    r1 = Request("mb", list(prompt), sp)
    r2 = Request("ms", list(prompt), sp, lora="strong")
    eng.add_request(r1)
    eng.add_request(r2)
    while not (r1.finished and r2.finished):
        eng.step()
    assert r1.output_tokens == base
    assert r2.output_tokens == strong


def test_bench_llm_smoke_mode():
    """CI gate for the scheduler: bench_llm.py --smoke must finish
    fast on CPU and report one dispatch per step for the mixed
    workload."""
    import json
    import subprocess
    import sys
    import os
    out = subprocess.run(
        [sys.executable, "bench_llm.py", "--smoke"],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["metric"] == "llm_mixed_smoke"
    assert row["detail"]["unified"]["dispatches_per_step"] == 1.0
    # ISSUE 2 gate: a unified tick through the Pallas ragged kernel
    # (interpret mode) is token-exact vs the gather path at temp 0
    assert row["detail"]["kernel_tick"]["token_exact"] is True
    # greedy agreement across the two engines (1.0 in practice; the
    # bound tolerates near-tie argmax flips, which are FP noise, not
    # scheduler bugs — see bench_mixed's docstring)
    assert row["detail"]["token_match"] >= 0.9
