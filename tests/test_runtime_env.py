"""Runtime env system: env applied at worker spawn, env-keyed worker
reuse. Reference parity: python/ray/_private/runtime_env/plugin.py:24,118
+ src/ray/raylet/worker_pool.h:224 (env-keyed idle pools)."""

import os
import sys
import textwrap

import pytest

import ray_tpu


def test_env_vars_applied(ray_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_TEST_FLAG": "hello42"}})
    def read_env():
        import os
        return os.environ.get("MY_TEST_FLAG")

    assert ray_tpu.get(read_env.remote()) == "hello42"


def test_env_isolation_between_envs(ray_start):
    """Tasks without the env never see its variables (distinct workers)."""
    @ray_tpu.remote(runtime_env={"env_vars": {"ISOLATED_VAR": "yes"}})
    def with_env():
        import os
        return os.environ.get("ISOLATED_VAR"), os.getpid()

    @ray_tpu.remote
    def without_env():
        import os
        return os.environ.get("ISOLATED_VAR"), os.getpid()

    v1, pid1 = ray_tpu.get(with_env.remote())
    v2, pid2 = ray_tpu.get(without_env.remote())
    assert v1 == "yes" and v2 is None
    assert pid1 != pid2


def test_env_keyed_worker_reuse(ray_start):
    """Same runtime env -> same worker reused; different env -> new one."""
    env_a = {"env_vars": {"POOL_TAG": "a"}}

    @ray_tpu.remote(runtime_env=env_a)
    def pid_a():
        import os
        return os.getpid()

    @ray_tpu.remote(runtime_env={"env_vars": {"POOL_TAG": "b"}})
    def pid_b():
        import os
        return os.getpid()

    a1 = ray_tpu.get(pid_a.remote())
    a2 = ray_tpu.get(pid_a.remote())
    b1 = ray_tpu.get(pid_b.remote())
    assert a1 == a2            # env-keyed reuse
    assert b1 != a1            # env mismatch -> different worker


def test_py_modules_module_driver_lacks(ray_start, tmp_path):
    """A task imports a module that does NOT exist on the driver's path —
    delivered via runtime_env py_modules."""
    mod_dir = tmp_path / "exotic_pkg"
    mod_dir.mkdir()
    (mod_dir / "__init__.py").write_text(
        textwrap.dedent("""
        SECRET = "from-runtime-env"
        def double(x):
            return 2 * x
        """))

    with pytest.raises(ImportError):
        import exotic_pkg  # noqa: F401

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_it():
        import exotic_pkg
        return exotic_pkg.SECRET, exotic_pkg.double(21)

    secret, doubled = ray_tpu.get(use_it.remote())
    assert secret == "from-runtime-env" and doubled == 42


def test_working_dir(ray_start, tmp_path):
    (tmp_path / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def read_file():
        import os
        with open("data.txt") as f:
            return os.path.basename(os.getcwd()), f.read()

    base, content = ray_tpu.get(read_file.remote())
    assert content == "payload"
    assert base == os.path.basename(str(tmp_path))


def test_actor_runtime_env(ray_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "actorval"}})
    class EnvActor:
        def read(self):
            import os
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote()) == "actorval"


def test_bad_runtime_env_fails_task(ray_start):
    @ray_tpu.remote(runtime_env={"working_dir": "/nonexistent/dir/xyz"})
    def never_runs():
        return 1

    with pytest.raises(Exception, match="working_dir|spawn"):
        ray_tpu.get(never_runs.remote(), timeout=60)


def test_registered_plugin_applies(ray_start):
    """An externally registered RuntimeEnvPlugin's key works end-to-end
    (reference parity: RuntimeEnvPluginManager, plugin.py:118)."""
    from ray_tpu.runtime_env import RuntimeEnvPlugin, register_plugin

    class StampPlugin(RuntimeEnvPlugin):
        name = "stamp"
        priority = 15

        async def create(self, value, ctx, node):
            ctx.env_vars["STAMP_FROM_PLUGIN"] = str(value).upper()

    register_plugin(StampPlugin())

    @ray_tpu.remote(runtime_env={"stamp": "hello"})
    def read():
        import os
        return os.environ.get("STAMP_FROM_PLUGIN")

    assert ray_tpu.get(read.remote()) == "HELLO"


def test_unknown_runtime_env_key_fails_loudly(ray_start):
    @ray_tpu.remote(runtime_env={"no_such_plugin": 1})
    def f():
        return 1

    with pytest.raises(Exception, match="no_such_plugin"):
        ray_tpu.get(f.remote(), timeout=60)


def test_working_dir_uri_cached_per_node(ray_start, tmp_path):
    """A storage-URI working_dir downloads ONCE per node and is reused
    by every later env with the same URI (per-node URI caching,
    reference: runtime-env agent URI cache)."""
    from ray_tpu.train import storage

    src = tmp_path / "wd"
    src.mkdir()
    (src / "data.txt").write_text("uri-cached-content")
    storage.upload_dir(str(src), "mock://renv/wd1")

    @ray_tpu.remote(runtime_env={"working_dir": "mock://renv/wd1",
                                 "env_vars": {"WD_ROUND": "1"}})
    def read1():
        return open("data.txt").read()

    @ray_tpu.remote(runtime_env={"working_dir": "mock://renv/wd1",
                                 "env_vars": {"WD_ROUND": "2"}})
    def read2():
        return open("data.txt").read()

    assert ray_tpu.get(read1.remote()) == "uri-cached-content"
    assert ray_tpu.get(read2.remote()) == "uri-cached-content"
    rt = ray_tpu.init(ignore_reinit_error=True)
    cache = rt.head_daemon._env_manager.node.cache
    assert cache.misses == 1 and cache.hits >= 1, (
        cache.hits, cache.misses)


def test_uv_env_builds_venv_worker(ray_start):
    """uv plugin: worker runs under a venv interpreter built on demand
    (create-on-demand + cache; uv binary optional, pip fallback)."""
    @ray_tpu.remote(runtime_env={"uv": {"packages": []}})
    def which_python():
        import sys
        return sys.executable

    exe = ray_tpu.get(which_python.remote(), timeout=120)
    assert "venv" in exe, exe


def test_conda_missing_binary_fails_loudly(ray_start, monkeypatch):
    @ray_tpu.remote(runtime_env={"conda": "someenv"})
    def f():
        return 1

    rt = ray_tpu.init(ignore_reinit_error=True)
    import shutil as _sh
    if _sh.which("conda") or os.environ.get("CONDA_EXE"):
        pytest.skip("conda present on this box")
    with pytest.raises(Exception, match="conda"):
        ray_tpu.get(f.remote(), timeout=60)


def test_image_uri_stub_wraps_spawn(ray_start, tmp_path):
    """image_uri propagates through a configured container prefix (the
    GKE/KubeRay hook); bare nodes without a prefix fail loudly."""
    rt = ray_tpu.init(ignore_reinit_error=True)
    daemon = rt.head_daemon
    from ray_tpu._private.config import get_config

    @ray_tpu.remote(runtime_env={"image_uri": "gcr.io/proj/img:1"})
    def containered():
        import os
        return os.environ.get("FAKE_CONTAINER_IMAGE")

    # no container runtime configured -> loud failure
    if not get_config().container_run_prefix:
        with pytest.raises(Exception, match="container"):
            ray_tpu.get(containered.remote(), timeout=60)
    # configure a fake runtime: env-wrapper stands in for podman/docker
    old = get_config().container_run_prefix
    get_config().container_run_prefix = "env FAKE_CONTAINER_IMAGE={image}"
    try:
        assert ray_tpu.get(containered.remote(),
                           timeout=120) == "gcr.io/proj/img:1"
    finally:
        get_config().container_run_prefix = old
