"""Runtime env system: env applied at worker spawn, env-keyed worker
reuse. Reference parity: python/ray/_private/runtime_env/plugin.py:24,118
+ src/ray/raylet/worker_pool.h:224 (env-keyed idle pools)."""

import os
import sys
import textwrap

import pytest

import ray_tpu


def test_env_vars_applied(ray_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_TEST_FLAG": "hello42"}})
    def read_env():
        import os
        return os.environ.get("MY_TEST_FLAG")

    assert ray_tpu.get(read_env.remote()) == "hello42"


def test_env_isolation_between_envs(ray_start):
    """Tasks without the env never see its variables (distinct workers)."""
    @ray_tpu.remote(runtime_env={"env_vars": {"ISOLATED_VAR": "yes"}})
    def with_env():
        import os
        return os.environ.get("ISOLATED_VAR"), os.getpid()

    @ray_tpu.remote
    def without_env():
        import os
        return os.environ.get("ISOLATED_VAR"), os.getpid()

    v1, pid1 = ray_tpu.get(with_env.remote())
    v2, pid2 = ray_tpu.get(without_env.remote())
    assert v1 == "yes" and v2 is None
    assert pid1 != pid2


def test_env_keyed_worker_reuse(ray_start):
    """Same runtime env -> same worker reused; different env -> new one."""
    env_a = {"env_vars": {"POOL_TAG": "a"}}

    @ray_tpu.remote(runtime_env=env_a)
    def pid_a():
        import os
        return os.getpid()

    @ray_tpu.remote(runtime_env={"env_vars": {"POOL_TAG": "b"}})
    def pid_b():
        import os
        return os.getpid()

    a1 = ray_tpu.get(pid_a.remote())
    a2 = ray_tpu.get(pid_a.remote())
    b1 = ray_tpu.get(pid_b.remote())
    assert a1 == a2            # env-keyed reuse
    assert b1 != a1            # env mismatch -> different worker


def test_py_modules_module_driver_lacks(ray_start, tmp_path):
    """A task imports a module that does NOT exist on the driver's path —
    delivered via runtime_env py_modules."""
    mod_dir = tmp_path / "exotic_pkg"
    mod_dir.mkdir()
    (mod_dir / "__init__.py").write_text(
        textwrap.dedent("""
        SECRET = "from-runtime-env"
        def double(x):
            return 2 * x
        """))

    with pytest.raises(ImportError):
        import exotic_pkg  # noqa: F401

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_it():
        import exotic_pkg
        return exotic_pkg.SECRET, exotic_pkg.double(21)

    secret, doubled = ray_tpu.get(use_it.remote())
    assert secret == "from-runtime-env" and doubled == 42


def test_working_dir(ray_start, tmp_path):
    (tmp_path / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def read_file():
        import os
        with open("data.txt") as f:
            return os.path.basename(os.getcwd()), f.read()

    base, content = ray_tpu.get(read_file.remote())
    assert content == "payload"
    assert base == os.path.basename(str(tmp_path))


def test_actor_runtime_env(ray_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "actorval"}})
    class EnvActor:
        def read(self):
            import os
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote()) == "actorval"


def test_bad_runtime_env_fails_task(ray_start):
    @ray_tpu.remote(runtime_env={"working_dir": "/nonexistent/dir/xyz"})
    def never_runs():
        return 1

    with pytest.raises(Exception, match="working_dir|spawn"):
        ray_tpu.get(never_runs.remote(), timeout=60)
