"""ThreadSanitizer gate for the native arena + shm channels.

Reference parity: the reference's C++ tests run under TSAN/ASAN in CI
(SURVEY.md §5 race detection). Builds src/tsan_stress.cc with
-fsanitize=thread and fails on any ThreadSanitizer report.
"""

import os
import shutil
import subprocess
import sys

import pytest

SRC = os.path.join(os.path.dirname(__file__), "..", "src")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_arena_and_channels_race_free_under_tsan(tmp_path):
    binary = tmp_path / "tsan_stress"
    build = subprocess.run(
        ["g++", "-fsanitize=thread", "-O1", "-g", "-std=c++17",
         "-pthread", "-o", str(binary),
         os.path.join(SRC, "tsan_stress.cc"),
         os.path.join(SRC, "arena_store.cc"),
         os.path.join(SRC, "shm_channel.cc")],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-3000:]

    run = subprocess.run(
        [str(binary)], capture_output=True, text=True, timeout=600,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=0"})
    report = run.stdout + run.stderr
    assert "WARNING: ThreadSanitizer" not in report, report[-6000:]
    assert run.returncode == 0, report[-3000:]
    assert "TSAN_STRESS_OK" in run.stdout
