"""Usage stats (reference parity: python/ray/_private/usage/usage_lib.py
record_library_usage / cluster metadata / periodic reporter — opt-in
here, file+KV sink instead of a usage server)."""

import json
import os

from ray_tpu._private import usage


def test_record_library_usage_process_local():
    usage.record_library_usage("_test_lib")
    usage.record_library_usage("_test_lib")      # idempotent
    assert "_test_lib" in usage.get_library_usages()


def test_library_imports_record_usage():
    import ray_tpu.train    # noqa: F401
    import ray_tpu.tune     # noqa: F401
    import ray_tpu.data     # noqa: F401
    libs = usage.get_library_usages()
    assert {"train", "tune", "data"} <= libs


def test_cluster_metadata_fields():
    meta = usage.cluster_metadata()
    assert meta["python_version"].count(".") >= 1
    assert "jax_version" in meta
    assert meta["source"] == "ray_tpu"


def test_reporter_snapshot_and_file(ray_start):
    import ray_tpu.train    # noqa: F401 — recorded usage asserted below
    client = ray_start.current_runtime().client
    usage.record_extra_usage_tag("test_tag", "42")
    rep = usage.UsageReporter(client, ray_start.current_runtime().session_name,
                              interval_s=3600)
    snap = rep.report_once()
    assert snap["extra_usage_tags"].get("test_tag") == "42"
    assert snap["num_nodes"] >= 1
    assert snap["total_resources"].get("CPU", 0) > 0
    # libraries recorded in THIS process appear in the snapshot
    assert "train" in snap["library_usages"]
    with open(rep._path) as f:
        on_disk = json.load(f)
    assert on_disk["extra_usage_tags"]["test_tag"] == "42"


def test_disabled_by_default():
    assert os.environ.get("RAY_TPU_USAGE_STATS") != "1"
    assert not usage.usage_stats_enabled()
