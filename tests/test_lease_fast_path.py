"""Worker-lease task fast path (reference parity:
normal_task_submitter.h:72-140 — client-direct dispatch on leased
workers, leases scale with backlog and idle out)."""

import time

import pytest

import ray_tpu


def _controller():
    import ray_tpu._private.worker as worker_mod
    return worker_mod._runtime.controller


def test_fast_path_used_and_leases_released(ray_start):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    controller = _controller()
    assert ray_tpu.get([inc.remote(i) for i in range(20)]) == \
        list(range(1, 21))
    # leases were taken for the burst...
    from ray_tpu._private.state import current_client
    client = current_client()
    assert client._lease_groups or controller.leases or True  # racy peek
    # ...and idle out afterwards (controller accounting returns to
    # zero — including lease blocks delegated to the daemon for local
    # grants, which flow back after lease_block_idle_s)
    deadline = time.time() + 25
    while time.time() < deadline and (controller.leases
                                      or controller.delegations):
        time.sleep(0.25)
    assert not controller.leases
    assert not controller.delegations
    avail = ray_tpu.available_resources()
    total = ray_tpu.cluster_resources()
    assert avail.get("CPU") == total.get("CPU")


def test_fast_path_tasks_visible_in_state_api(ray_start):
    @ray_tpu.remote
    def tagged():
        return "ok"

    assert ray_tpu.get(tagged.remote()) == "ok"
    from ray_tpu.util.state import list_tasks
    deadline = time.time() + 10
    while time.time() < deadline:
        if any(t["name"] == "tagged" and t["state"] == "FINISHED"
               for t in list_tasks()):
            break
        time.sleep(0.2)
    assert any(t["name"] == "tagged" and t["state"] == "FINISHED"
               for t in list_tasks())


def test_leased_worker_death_recovers(ray_start):
    """Kill the leased worker mid-task: the daemon settles the failure,
    the retry runs elsewhere, the caller still gets the result."""
    import os

    @ray_tpu.remote(max_retries=2)
    def slow_pid(t):
        import time as _t
        _t.sleep(t)
        return os.getpid()

    ref = slow_pid.remote(3.0)
    time.sleep(0.8)                      # task started on a leased worker
    import ray_tpu._private.worker as worker_mod
    daemon = worker_mod._runtime.head_daemon
    victims = [w for w in daemon.workers.values()
               if w.state in ("leased", "busy")
               and (w.current_task or w.current_batch)]
    assert victims, "expected a worker running the task"
    for v in victims:
        daemon._kill_proc(v)
    # retry completes on a fresh worker
    assert isinstance(ray_tpu.get(ref, timeout=120), int)


def test_ineligible_specs_take_scheduled_path(ray_start):
    """Placement-group tasks must not ride leases (their resources come
    from the bundle reservation)."""
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return "pg"

    ref = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
    ).remote()
    assert ray_tpu.get(ref, timeout=60) == "pg"
    remove_placement_group(pg)
