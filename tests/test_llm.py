"""LLM inference: paged attention, engine correctness, OpenAI serving.

The gold test: greedy incremental decode through the paged engine must
EXACTLY match argmax over a full forward pass re-run each step — this
pins prefill scatter, page tables, decode masking, RoPE positions, and
sampling all at once.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models import llama
from ray_tpu.llm import (ByteTokenizer, EngineConfig, InferenceEngine,
                         Request, SamplingParams)


def make_engine(**over):
    cfg = llama.config("debug", dtype=jnp.float32)
    kw = dict(model=cfg, max_batch_size=4, page_size=8, num_pages=64,
              prefill_buckets=(16, 32, 64))
    kw.update(over)
    return InferenceEngine(EngineConfig(**kw))


# ------------------------------------------------------------- paged attn

def test_paged_attention_matches_dense():
    from ray_tpu.ops.paged_attention import (paged_attention_on_gathered,
                                             scatter_kv, gather_kv)
    rng = np.random.default_rng(0)
    B, CTX, L, KVH, H, D = 2, 24, 3, 2, 4, 16
    num_pages, page = 16, 8
    k_pages = jnp.zeros((L, num_pages, page, KVH, D))
    v_pages = jnp.zeros((L, num_pages, page, KVH, D))
    # seq 0 gets pages [0,1,2], seq 1 gets [3,4,5]
    tables = jnp.asarray([[0, 1, 2], [3, 4, 5]], jnp.int32)
    lens = np.array([20, 13])
    kd = rng.normal(size=(B, CTX, L, KVH, D)).astype(np.float32)
    vd = rng.normal(size=(B, CTX, L, KVH, D)).astype(np.float32)
    for b in range(B):
        rows_k = jnp.asarray(kd[b, :lens[b]])
        rows_v = jnp.asarray(vd[b, :lens[b]])
        t = jnp.tile(tables[b][None], (lens[b], 1))
        pos = jnp.arange(lens[b])
        k_pages, v_pages = scatter_kv(
            k_pages, v_pages, rows_k, rows_v, t, pos,
            jnp.ones(lens[b], bool))
    gk, gv = gather_kv(k_pages, v_pages, tables)   # [L, B, ctx, KVH, D]
    q = jnp.asarray(rng.normal(size=(B, H, D)).astype(np.float32))
    for layer in range(L):
        out = paged_attention_on_gathered(
            q, gk[layer], gv[layer], jnp.asarray(lens, jnp.int32))
        # dense reference with GQA repeat
        for b in range(B):
            kk = np.repeat(kd[b, :lens[b], layer], H // KVH, axis=1)
            vv = np.repeat(vd[b, :lens[b], layer], H // KVH, axis=1)
            qq = np.asarray(q[b])                        # [H, D]
            sc = np.einsum("hd,chd->hc", qq, kk) / np.sqrt(D)
            p = np.exp(sc - sc.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            ref = np.einsum("hc,chd->hd", p, vv)
            np.testing.assert_allclose(np.asarray(out[b]), ref,
                                       rtol=2e-4, atol=2e-5)


def test_scatter_masks_invalid_rows_to_scratch():
    from ray_tpu.ops.paged_attention import scatter_kv
    k_pages = jnp.zeros((1, 4, 1, 2, 2))           # [L, pages, KVH, page, D]
    v_pages = jnp.zeros((1, 4, 1, 2, 2))
    rows = jnp.ones((1, 1, 1, 2))
    t = jnp.asarray([[0, 1]], jnp.int32)
    k2, v2 = scatter_kv(k_pages, v_pages, rows, rows, t,
                        jnp.asarray([0]), jnp.asarray([False]))
    assert float(jnp.abs(k2[:, :3]).sum()) == 0.0  # real pages untouched
    assert float(jnp.abs(k2[:, 3]).sum()) > 0.0    # scratch page took it


# ---------------------------------------------------------------- engine

def test_incremental_decode_matches_full_forward():
    eng = make_engine()
    cfg = eng.model_cfg
    rng = np.random.default_rng(1)
    prompts = [list(rng.integers(2, 200, n)) for n in (5, 9, 17)]
    reqs = eng.generate(prompts, SamplingParams(max_tokens=6,
                                                temperature=0.0))
    fwd = jax.jit(lambda p, t: llama.forward(cfg, p, t))
    for req, prompt in zip(reqs, prompts):
        toks = list(prompt)
        gold = []
        for _ in range(6):
            logits = fwd(eng.params, jnp.asarray([toks], jnp.int32))
            nxt = int(jnp.argmax(logits[0, -1]))
            gold.append(nxt)
            toks.append(nxt)
        assert req.output_tokens == gold


def test_continuous_batching_staggered_arrivals():
    eng = make_engine(max_batch_size=2)
    rng = np.random.default_rng(2)
    r1 = Request("a", list(rng.integers(2, 200, 4)),
                 SamplingParams(max_tokens=10))
    r2 = Request("b", list(rng.integers(2, 200, 6)),
                 SamplingParams(max_tokens=3))
    r3 = Request("c", list(rng.integers(2, 200, 5)),
                 SamplingParams(max_tokens=4))
    eng.add_request(r1)
    eng.add_request(r2)
    eng.add_request(r3)          # must wait: only 2 slots
    eng.step()
    assert eng.num_active() == 2 and len(eng.waiting) == 1
    while eng.has_work():
        eng.step()
    assert r1.finished and r2.finished and r3.finished
    assert len(r1.output_tokens) == 10
    assert len(r2.output_tokens) == 3
    assert len(r3.output_tokens) == 4
    # all pages reclaimed
    assert eng.stats()["free_pages"] == eng.stats()["total_pages"]


def test_admission_control_blocks_on_cache_pressure():
    eng = make_engine(num_pages=9)   # 8 usable pages of 8 tokens
    r1 = Request("a", [5] * 20, SamplingParams(max_tokens=12))  # 4 pages
    r2 = Request("b", [6] * 20, SamplingParams(max_tokens=12))  # 4 pages
    r3 = Request("c", [7] * 20, SamplingParams(max_tokens=12))
    for r in (r1, r2, r3):
        eng.add_request(r)
    eng.step()
    assert eng.num_active() == 2 and len(eng.waiting) == 1
    while eng.has_work():
        eng.step()
    assert r3.finished


def test_sampling_temperature_and_top_p():
    eng = make_engine()
    prompts = [[5, 6, 7, 8]]
    greedy1 = eng.generate(prompts, SamplingParams(max_tokens=5))
    greedy2 = eng.generate(prompts, SamplingParams(max_tokens=5))
    assert greedy1[0].output_tokens == greedy2[0].output_tokens
    hot = eng.generate(prompts * 2, SamplingParams(
        max_tokens=12, temperature=5.0, top_p=0.95))
    assert hot[0].output_tokens != hot[1].output_tokens
    assert all(0 <= t < eng.model_cfg.vocab_size
               for t in hot[0].output_tokens)


def test_seeded_sampling_reproducible_across_engines():
    """ISSUE 9 satellite: SamplingParams.seed makes the sampled path
    fully reproducible — two fresh engines (same weights seed), same
    prompt, same seed → identical token sequences; a different seed
    diverges. Without an explicit seed, the seed derives from the
    request id, so identical requests under DIFFERENT ids still
    diverge (a hot sampled batch must not collapse to one sequence)."""
    p = SamplingParams(max_tokens=10, temperature=0.9, top_p=0.9,
                      seed=123)
    a = make_engine(seed=7).generate([[5, 6, 7, 8]], p)
    b = make_engine(seed=7).generate([[5, 6, 7, 8]], p)
    assert a[0].output_tokens == b[0].output_tokens
    c = make_engine(seed=7).generate(
        [[5, 6, 7, 8]],
        SamplingParams(max_tokens=10, temperature=0.9, top_p=0.9,
                       seed=124))
    assert c[0].output_tokens != a[0].output_tokens


def test_seeded_sampled_replay_is_token_exact():
    """The failover-continuation property (ISSUE 9), engine-level:
    re-submitting prompt + the first k sampled outputs as the new
    prompt (same seed, max_tokens decremented) reproduces the
    remaining tokens EXACTLY — sampling keys derive from (seed,
    absolute token index), so the replay's prefill samples what the
    original's decode ticks would have."""
    prompt = [5, 6, 7, 8, 9]
    p = SamplingParams(max_tokens=10, temperature=0.8, top_p=0.95,
                      seed=999)
    full = make_engine(seed=7).generate(
        [prompt], p)[0].output_tokens
    assert len(full) == 10
    for k in (1, 4, 9):
        cont = make_engine(seed=7).generate(
            [prompt + full[:k]],
            SamplingParams(max_tokens=10 - k, temperature=0.8,
                           top_p=0.95, seed=999))[0].output_tokens
        assert cont == full[k:], (k, cont, full)


def test_deadline_expires_waiting_and_running_requests():
    """ISSUE 9 deadline propagation, engine half: a request past its
    deadline finishes with finish_reason="deadline" — straight out of
    the waiting queue if it never got a slot, or aborted at the next
    fold boundary if it was decoding (pages freed, slot reusable)."""
    import time as _time

    eng = make_engine()
    # waiting-queue expiry: deadline already past at the first tick
    r = Request("ddl-wait", [5, 6, 7], SamplingParams(max_tokens=5),
                deadline=_time.monotonic() - 1.0)
    eng.add_request(r)
    touched = eng.step()
    assert r.finished and r.finish_reason == "deadline"
    assert r in touched              # the finish event reaches streams
    assert not r.output_tokens

    # running-slot expiry: admit normally, then expire mid-decode
    r2 = Request("ddl-run", [5, 6, 7], SamplingParams(max_tokens=40),
                 deadline=_time.monotonic() + 3600.0)
    eng.add_request(r2)
    for _ in range(4):
        eng.step()
    assert not r2.finished and r2.output_tokens
    free_before = eng.allocator.free_pages
    r2.deadline = _time.monotonic() - 1.0
    eng.step()
    assert r2.finished and r2.finish_reason == "deadline"
    assert eng.allocator.free_pages > free_before   # pages freed
    # the engine is still healthy: a fresh request completes
    ok = eng.generate([[9, 8, 7]], SamplingParams(max_tokens=3))
    assert ok[0].finish_reason is not None
    kinds = [e["event"] for e in eng.telemetry.recorder.events()]
    assert "deadline_abort" in kinds


def test_stop_tokens():
    eng = make_engine()
    reqs = eng.generate([[5, 6, 7]], SamplingParams(max_tokens=30))
    tok = reqs[0].output_tokens[2]
    reqs2 = eng.generate([[5, 6, 7]], SamplingParams(
        max_tokens=30, stop_token_ids=(tok,)))
    assert reqs2[0].finish_reason == "stop"
    assert reqs2[0].output_tokens[-1] == tok
    assert len(reqs2[0].output_tokens) <= 3


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer(300)
    ids = tok.encode("hello world")
    assert tok.decode(ids) == "hello world"
    small = ByteTokenizer(256)        # debug vocab: folded bytes
    ids = small.encode("hi")
    assert all(i < 256 for i in ids)
    chat = tok.apply_chat_template(
        [{"role": "user", "content": "hi"}])
    assert "assistant" in chat


# --------------------------------------------------------------- serving

@pytest.mark.usefixtures("ray_start")
def test_openai_app_http(ray_start):
    import requests
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, build_openai_app

    app = build_openai_app({"llm_configs": [LLMConfig(
        model_id="m0", model_source="debug",
        engine_kwargs=dict(max_batch_size=4, page_size=8, num_pages=128,
                           prefill_buckets=(32, 64)))]})
    try:
        serve.run(app, name="llm", route_prefix="/",
                  http_options=serve.HTTPOptions(port=8126),
                  timeout_s=180)
        r = requests.get("http://127.0.0.1:8126/v1/models", timeout=30)
        assert r.status_code == 200
        assert r.json()["data"][0]["id"] == "m0"
        r = requests.post(
            "http://127.0.0.1:8126/v1/chat/completions",
            json={"model": "m0", "max_tokens": 6,
                  "messages": [{"role": "user", "content": "hey"}]},
            timeout=120)
        assert r.status_code == 200
        body = r.json()
        assert body["usage"]["completion_tokens"] <= 6
        assert body["choices"][0]["message"]["role"] == "assistant"
        r = requests.post(
            "http://127.0.0.1:8126/v1/chat/completions",
            json={"model": "nope", "messages": []}, timeout=60)
        assert r.status_code == 404
        # /stats smoke (ISSUE 4): tick-pipeline telemetry is
        # observable in serving — overlap ratio + lag/drain counters
        r = requests.get("http://127.0.0.1:8126/stats", timeout=30)
        assert r.status_code == 200
        eng_stats = r.json()["models"]["m0"]
        tt = eng_stats["tick_times"]
        assert {"wall_ms_avg", "host_ms_avg", "device_ms_avg",
                "overlap_ratio", "lagged_ticks",
                "drains"} <= set(tt)
        assert tt["async_readback"] is True
        assert eng_stats["dispatches"] >= 1
    finally:
        serve.shutdown()


def test_openai_streaming_sse(ray_start):
    """stream=true returns Server-Sent Events with incremental deltas,
    relayed proxy -> router replica -> model server replica over the
    actor streaming plane."""
    import json

    import requests
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, build_openai_app

    app = build_openai_app({"llm_configs": [LLMConfig(
        model_id="m0", model_source="debug",
        engine_kwargs=dict(max_batch_size=4, page_size=8, num_pages=128,
                           prefill_buckets=(32, 64)))]})
    try:
        serve.run(app, name="llm", route_prefix="/",
                  http_options=serve.HTTPOptions(port=8127),
                  timeout_s=180)
        r = requests.post(
            "http://127.0.0.1:8127/v1/chat/completions",
            json={"model": "m0", "max_tokens": 5, "stream": True,
                  "messages": [{"role": "user", "content": "hey"}]},
            stream=True, timeout=120)
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        events = []
        for line in r.iter_lines():
            if line.startswith(b"data: "):
                events.append(line[len(b"data: "):])
        assert events[-1] == b"[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        assert 1 <= len(chunks) <= 6
        assert chunks[0]["object"] == "chat.completion.chunk"
        assert chunks[-1]["choices"][0]["finish_reason"] is not None
    finally:
        serve.shutdown()


def test_sampling_top_k_and_repetition_penalty():
    """top_k masks everything outside the k best; repetition penalty
    (CTRL) suppresses seen tokens (VERDICT r3 weak #7)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.llm._internal.engine import _sample

    logits = jnp.asarray([[0.0, 5.0, 4.0, -2.0, 1.0]], jnp.float32)
    key = jax.random.PRNGKey(0)
    ones = jnp.ones(1, jnp.float32)

    # top_k=1 pins sampling to the argmax even at high temperature
    for seed in range(5):
        tok = _sample(logits, jax.random.PRNGKey(seed), ones * 5.0,
                      ones, top_ks=jnp.asarray([1]),
                      rep_pens=ones, seen=jnp.zeros((1, 5), bool))
        assert int(tok[0]) == 1

    # top_k=2 at high temperature: only the two best ever sampled
    picks = {int(_sample(logits, jax.random.PRNGKey(s), ones * 5.0,
                         ones, top_ks=jnp.asarray([2]), rep_pens=ones,
                         seen=jnp.zeros((1, 5), bool))[0])
             for s in range(30)}
    assert picks <= {1, 2} and len(picks) == 2

    # repetition penalty: the seen argmax (token 1) is suppressed below
    # the runner-up; greedy then picks token 2
    seen = jnp.zeros((1, 5), bool).at[0, 1].set(True)
    tok = _sample(logits, key, jnp.zeros(1), ones,
                  top_ks=jnp.zeros(1, jnp.int32),
                  rep_pens=jnp.asarray([3.0]), seen=seen)
    assert int(tok[0]) == 2


def test_engine_repetition_penalty_no_repeats():
    """End-to-end: a huge penalty forbids re-emitting prompt or
    generated tokens — every output token is fresh."""
    import jax.numpy as jnp
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              SamplingParams)
    from ray_tpu.models import llama

    cfg = llama.config("debug", dtype=jnp.float32)
    eng = InferenceEngine(EngineConfig(model=cfg, max_batch_size=2,
                                       num_pages=64, seed=11))
    prompt = [7, 8, 9, 10]
    out = eng.generate([prompt], SamplingParams(
        max_tokens=10, repetition_penalty=1000.0))[0].output_tokens
    assert len(out) == 10
    assert len(set(out)) == len(out), out          # no repeats
    assert not (set(out) & set(prompt)), out       # prompt suppressed


def test_multi_lora_batched_adapters():
    """Multi-LoRA serving: different slots of one batch run different
    adapters; a zero adapter is an exact no-op (VERDICT r3 weak #7)."""
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine, Request,
                                              SamplingParams)
    from ray_tpu.models import llama

    cfg = llama.config("debug", dtype=jnp.float32)
    eng = InferenceEngine(EngineConfig(model=cfg, max_batch_size=4,
                                       num_pages=64, seed=4))
    L, h, q_dim, r = cfg.n_layers, cfg.hidden, cfg.q_dim, 4
    rng = np.random.default_rng(0)
    eng.register_lora("strong", {
        "wq": (rng.normal(0, 0.5, (L, h, r)),
               rng.normal(0, 0.5, (r, q_dim)) * np.ones((L, 1, 1))),
    })
    eng.register_lora("zero", {"wq": (np.zeros((L, h, r)),
                                      np.zeros((L, r, q_dim)))})
    prompt = [3, 4, 5, 6]
    sp = SamplingParams(max_tokens=6)

    def run(lora, rid):
        req = Request(rid, list(prompt), sp, lora=lora)
        eng.add_request(req)
        while not req.finished:
            eng.step()
        return req.output_tokens

    base = run(None, "base")
    strong = run("strong", "strong1")
    zero = run("zero", "zero1")
    assert zero == base, (zero, base)        # zero adapter = exact no-op
    assert strong != base, strong            # a real adapter changes logits

    # mixed batch: base + strong simultaneously must reproduce their
    # solo outputs (per-slot adapter gather is actually per-slot)
    r1 = Request("mix-base", list(prompt), sp)
    r2 = Request("mix-strong", list(prompt), sp, lora="strong")
    eng.add_request(r1)
    eng.add_request(r2)
    while not (r1.finished and r2.finished):
        eng.step()
    assert r1.output_tokens == base
    assert r2.output_tokens == strong

    with pytest.raises(ValueError, match="unknown LoRA"):
        eng.add_request(Request("bad", [1, 2], sp, lora="nope"))


def test_data_llm_batch_lora_column(ray_start):
    """data.llm batch inference: rows pick adapters via a 'lora'
    column, registered from the processor config."""
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu import data
    from ray_tpu.data.llm import LLMEngineProcessorConfig, \
        build_llm_processor
    from ray_tpu.models import llama

    cfg = llama.config("debug", dtype=jnp.float32)
    L, h, q, r = cfg.n_layers, cfg.hidden, cfg.q_dim, 4
    rng = np.random.default_rng(0)
    proc = build_llm_processor(LLMEngineProcessorConfig(
        model_source=cfg,
        engine_kwargs={"num_pages": 64, "seed": 2},
        sampling_params={"max_tokens": 4},
        lora_adapters={"styleA": {
            "wq": (rng.normal(0, 0.5, (L, h, r)),
                   rng.normal(0, 0.5, (L, r, q)))}},
        batch_size=4))
    ds = data.from_items([
        {"prompt": "hello", "lora": ""},
        {"prompt": "hello", "lora": "styleA"},
    ])
    rows = proc(ds).take_all()
    assert len(rows) == 2
    assert rows[0]["generated_tokens"] != rows[1]["generated_tokens"]


def test_deployment_chips_follow_engine_mesh():
    """accelerator_type replicas request tp*pp chips (the reference
    sizes vLLM worker placement the same way, vllm_models.py:123-139)."""
    from ray_tpu.llm import LLMConfig, build_llm_deployment

    app = build_llm_deployment(LLMConfig(
        model_id="m", accelerator_type="TPU-V5E",
        engine_kwargs={"mesh": {"tp": 2, "pp": 2, "fsdp": 1}}))
    assert app._deployment.config.ray_actor_options["num_tpus"] == 4

    app1 = build_llm_deployment(LLMConfig(
        model_id="m2", accelerator_type="TPU-V5E"))
    assert app1._deployment.config.ray_actor_options["num_tpus"] == 1


def test_multi_step_decode_matches_single_step():
    """decode_steps_per_call=K runs K decode iterations in ONE
    dispatch (the per-dispatch-overhead amortizer for tunnel-bound
    chips): greedy and penalty decode are token-exact vs K=1, budgets
    clamp exactly at max_tokens, and EOS mid-scan truncates."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(2, 250, 6 + i).tolist() for i in range(3)]

    def gen(k, **sp):
        eng = make_engine(decode_steps_per_call=k,
                          enable_prefix_caching=False)
        reqs = eng.generate([list(p) for p in prompts],
                            SamplingParams(**sp))
        return [r.output_tokens for r in reqs]

    assert gen(4, max_tokens=13) == gen(1, max_tokens=13)
    assert gen(4, max_tokens=13, repetition_penalty=1.3) == \
        gen(1, max_tokens=13, repetition_penalty=1.3)
    assert all(len(o) == 5 for o in gen(8, max_tokens=5))
    # stop tokens truncate mid-scan
    base = gen(1, max_tokens=20)
    stop = base[0][4]
    stopped = gen(4, max_tokens=20, stop_token_ids=[stop])
    ref = gen(1, max_tokens=20, stop_token_ids=[stop])
    assert stopped == ref


def test_async_readback_token_exact_mixed_finishes():
    """ISSUE 4 lagged retirement: the pipelined engine must match the
    sync engine token-for-token (and finish_reason-for-finish_reason)
    on a mixed batch whose requests retire at DIFFERENT ticks via
    max_tokens, a stop token, and a penalized stream — each
    length-finish happens while its successor tick is already in
    flight, so the one-token over-generation discard and the drain
    barrier are both exercised repeatedly."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(2, 200, n).tolist() for n in (5, 11, 7, 16)]

    def run(async_rb, stop_tok):
        eng = make_engine(async_readback=async_rb,
                          enable_prefix_caching=False)
        params = [SamplingParams(max_tokens=6),
                  SamplingParams(max_tokens=13),
                  SamplingParams(max_tokens=20,
                                 stop_token_ids=(stop_tok,)),
                  SamplingParams(max_tokens=9,
                                 repetition_penalty=1.3)]
        reqs = [Request(f"x{i}", list(p), params[i])
                for i, p in enumerate(prompts)]
        for r in reqs:
            eng.add_request(r)
        while eng.has_work():
            eng.step()
        assert eng.stats()["free_pages"] == eng.stats()["total_pages"]
        return eng, [(r.output_tokens, r.finish_reason) for r in reqs]

    # pick the stop token from a reference pass so request 2 really
    # stops mid-stream, several ticks after request 0 retired
    _, ref = run(False, stop_tok=-1)
    stop_tok = ref[2][0][4]
    eng_s, out_sync = run(False, stop_tok)
    eng_a, out_async = run(True, stop_tok)
    assert out_async == out_sync
    assert out_async[2][1] == "stop"
    tt = eng_a.stats()["tick_times"]
    # the pipeline actually ran: folds lagged and retirements drained
    assert tt["lagged_ticks"] > 0 and tt["drains"] > 0
    assert eng_s.stats()["tick_times"]["lagged_ticks"] == 0


def test_async_finish_while_successor_in_flight():
    """Tightest lag case: a single request whose final token folds
    while the (over-generating) successor tick is in flight — output
    must truncate exactly at max_tokens, the discarded token must not
    leak, and the successor's KV write stays inside the slot's pages
    (the engine asserts that invariant at every fold)."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(2, 200, 9).tolist()
    outs = {}
    for async_rb in (False, True):
        eng = make_engine(async_readback=async_rb)
        req = Request("one", list(prompt), SamplingParams(max_tokens=2))
        eng.add_request(req)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
        assert req.finished and req.finish_reason == "length"
        assert len(req.output_tokens) == 2
        outs[async_rb] = (req.output_tokens, steps)
    assert outs[True][0] == outs[False][0]
    # the async run needed exactly one extra step: the lagged fold
    assert outs[True][1] == outs[False][1] + 1


def test_abort_drain_does_not_strand_finishes():
    """An abort-triggered drain folds the in-flight tick OUTSIDE any
    step() — if that fold retires ANOTHER request, its finish event
    must not be stranded: has_work() stays true until the next step
    delivers it through the touched list (the server pump parks on
    has_work, so a stranded finish would hang its stream consumer)."""
    rng = np.random.default_rng(9)
    eng = make_engine(max_batch_size=2, enable_prefix_caching=False)
    r1 = Request("a", rng.integers(2, 200, 5).tolist(),
                 SamplingParams(max_tokens=9))
    r2 = Request("b", rng.integers(2, 200, 7).tolist(),
                 SamplingParams(max_tokens=4))
    eng.add_request(r1)
    eng.add_request(r2)
    # step until r2's FINAL token is in flight but not yet folded
    while not (eng._inflight is not None
               and len(r2.output_tokens) == 3):
        eng.step()
    assert eng.abort("a")
    # the abort's drain folded the in-flight tick: r2 finished
    # outside step(), its event parked in _pending_touched
    assert r2.finished and r2.finish_reason == "length"
    assert len(r2.output_tokens) == 4
    assert eng.has_work()               # one more step delivers it
    touched = eng.step()
    assert r2 in touched
    assert not eng.has_work()
    assert eng.stats()["free_pages"] == eng.stats()["total_pages"]


def test_async_stream_order_preserved():
    """ISSUE 4 server contract: the one-tick lag must not reorder,
    drop, or duplicate streamed chunks — two concurrent SSE-style
    streams through the engine pump must each reconstruct exactly
    their request's decoded output."""
    import asyncio

    from ray_tpu.llm._internal.server import LLMServerImpl

    srv = LLMServerImpl({
        "model_id": "m0", "model_source": "debug",
        "engine_kwargs": dict(max_batch_size=4, page_size=8,
                              num_pages=128, prefill_buckets=(16, 32))})
    assert srv.engine._async            # pipeline on by default

    async def consume(prompt_text, max_tokens):
        toks = srv.tokenizer.encode(prompt_text)
        deltas = []
        finishes = 0
        async for _, delta, finished, reason in srv._generate_stream(
                toks, SamplingParams(max_tokens=max_tokens)):
            if not delta and not finished:
                continue       # the SSE wrappers drop text-less
            deltas.append(delta)   # events (tokens ride them for the
            finishes += finished   # failover relay — ISSUE 9)
        return deltas, finishes

    async def main():
        out = await asyncio.gather(consume("hello world", 7),
                                   consume("quite different", 11))
        srv._pump.cancel()
        return out

    (d1, f1), (d2, f2) = asyncio.run(main())
    assert f1 == 1 and f2 == 1          # exactly one finish each
    # every chunk except possibly the closing one carries new text
    assert all(d for d in d1[:-1]) and all(d for d in d2[:-1])

    # byte-exact reconstruction vs a SYNCHRONOUS reference engine:
    # the lagged stream may deliver chunks later, but never permuted,
    # duplicated, or dropped (greedy decode is batching-independent,
    # so solo sync runs are the gold text)
    ref = InferenceEngine(EngineConfig(
        model="debug", max_batch_size=4, page_size=8, num_pages=128,
        prefill_buckets=(16, 32), async_readback=False))
    for deltas, (text, n) in zip(
            (d1, d2), (("hello world", 7), ("quite different", 11))):
        out = ref.generate([srv.tokenizer.encode(text)],
                           SamplingParams(max_tokens=n))
        assert "".join(deltas) == srv.tokenizer.decode(
            out[0].output_tokens)
    tt = srv.engine.stats()["tick_times"]
    assert tt["lagged_ticks"] > 0       # streams rode the pipeline


def test_multi_step_decode_composes_with_prefix_cache():
    rng = np.random.default_rng(7)
    shared = rng.integers(2, 250, 24).tolist()
    prompts = [shared + [5], shared + [9, 11]]

    def gen(k, prefix):
        eng = make_engine(decode_steps_per_call=k, page_size=8,
                          num_pages=96, enable_prefix_caching=prefix)
        outs = []
        for p in prompts:
            outs.append(eng.generate(
                [list(p)], SamplingParams(max_tokens=10)
            )[0].output_tokens)
        return outs

    assert gen(4, True) == gen(1, False)
