"""racelint analyzer property tests (ISSUE 18, tools/racelint).

Per-rule synthetic modules (positive AND negative cases, cross-method
entry-lockset inference, async one-hop propagation) so rule
regressions are caught without running against ray_tpu/ — plus the
tier-1 repo gates: the shipped baseline is small and justified,
`python -m tools.racelint ray_tpu` is clean against it, and the
engine/serving-LLM planes hold a ZERO-baseline bar.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.racelint import analyze_paths, load_baseline
from tools.racelint.rules import ALL_RULES

REPO = Path(__file__).resolve().parent.parent


def _lint(tmp_path, source, name="mod.py", select=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analyze_paths([str(p)], root=str(tmp_path), select=select)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------------ RL001

def test_rl001_unlocked_writer_races_locked(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._step_lock = threading.Lock()
                self.waiting = []

            def step(self):
                with self._step_lock:
                    self.waiting = [r for r in self.waiting
                                    if not r.finished]

            def add_request(self, r):
                self.waiting.append(r)
    """, select={"RL001"})
    assert len(fs) == 1
    assert fs[0].func == "Engine.add_request"
    assert "waiting" in fs[0].detail


def test_rl001_all_writers_locked_clean(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._step_lock = threading.Lock()
                self.waiting = []

            def step(self):
                with self._step_lock:
                    self.waiting = []

            def add_request(self, r):
                with self._step_lock:
                    self.waiting.append(r)
    """, select={"RL001"})
    assert fs == []


def test_rl001_init_writes_exempt(tmp_path):
    """__init__ builds state before any thread can see it."""
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self.count += 1
    """, select={"RL001"})
    assert fs == []


def test_rl001_cross_method_entry_lockset(tmp_path):
    """A private helper called only under the lock inherits the
    caller's lock set — its writes count as locked."""
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def step(self):
                with self._lock:
                    self._rebuild()

            def other(self):
                with self._lock:
                    self._rebuild()

            def _rebuild(self):
                self.items = []
    """, select={"RL001"})
    assert fs == []


# ------------------------------------------------------------------ RL002

@pytest.mark.parametrize("body,flagged", [
    ("time.sleep(0.5)", True),
    ("requests.get(url)", True),
    ("self.engine.step()", True),
    ("self.engine.stats()", True),
    ("await asyncio.sleep(0.5)", False),
    ("self.engine.has_work()", False),      # not a step-lock entry point
], ids=["sleep", "http", "engine_step", "engine_stats",
        "async_sleep", "lock_free_read"])
def test_rl002_blocking_in_async_def(tmp_path, body, flagged):
    fs = _lint(tmp_path, f"""
        import asyncio
        import time
        import requests

        class Server:
            async def handler(self, url):
                {body}
    """, select={"RL002"})
    assert ("RL002" in _rules(fs)) is flagged


def test_rl002_lock_acquire_in_async_def(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()

            async def scrape(self):
                with self._lock:
                    return 1
    """, select={"RL002"})
    assert len(fs) == 1
    assert "with:" in fs[0].detail


def test_rl002_one_hop_sync_helper(tmp_path):
    """async -> sync helper that blocks is flagged at the call site;
    a helper that routes through run_in_executor is loop-aware."""
    fs = _lint(tmp_path, """
        import asyncio
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()

            def _slow(self):
                with self._lock:
                    return 1

            def _offloaded(self, rid):
                try:
                    asyncio.get_running_loop().run_in_executor(
                        None, self.engine.abort, rid)
                except RuntimeError:
                    self.engine.abort(rid)

            async def bad(self):
                return self._slow()

            async def ok(self, rid):
                self._offloaded(rid)
    """, select={"RL002"})
    assert len(fs) == 1
    assert fs[0].func == "Server.bad"


def test_rl002_unbounded_queue_get(tmp_path):
    fs = _lint(tmp_path, """
        class Worker:
            async def pull(self):
                return self.queue.get()
    """, select={"RL002"})
    assert len(fs) == 1
    assert "queue" in fs[0].message


def test_rl002_asyncio_field_receiver_clean(tmp_path):
    """Methods on an asyncio-constructed field return awaitables —
    they never block the loop (the util/queue.py false positive)."""
    fs = _lint(tmp_path, """
        import asyncio

        class QueueActor:
            def __init__(self):
                self._q = asyncio.Queue(maxsize=8)

            async def get(self, timeout):
                return await asyncio.wait_for(self._q.get(), timeout)
    """, select={"RL002"})
    assert fs == []


def test_rl002_module_level_async_fn(tmp_path):
    fs = _lint(tmp_path, """
        import time

        async def poll():
            time.sleep(1.0)
    """, select={"RL002"})
    assert len(fs) == 1
    assert fs[0].func == "poll"


# ------------------------------------------------------------------ RL003

def test_rl003_lock_order_cycle(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Fleet:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def route(self):
                with self._a:
                    with self._b:
                        pass

            def rebalance(self):
                with self._b:
                    with self._a:
                        pass
    """, select={"RL003"})
    assert len(fs) == 1
    assert "cycle" in fs[0].detail


def test_rl003_consistent_order_clean(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Fleet:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def route(self):
                with self._a:
                    with self._b:
                        pass

            def rebalance(self):
                with self._a:
                    with self._b:
                        pass
    """, select={"RL003"})
    assert fs == []


def test_rl003_cross_method_cycle_via_entry_lockset(tmp_path):
    """The inversion hides in a private helper whose entry lock set
    comes from its only call site."""
    fs = _lint(tmp_path, """
        import threading

        class Fleet:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def route(self):
                with self._a:
                    self._inner()

            def _inner(self):
                with self._b:
                    pass

            def rebalance(self):
                with self._b:
                    with self._a:
                        pass
    """, select={"RL003"})
    assert len(fs) == 1


# ------------------------------------------------------------------ RL004

def test_rl004_unlocked_iteration_of_locked_container(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def step(self):
                with self._lock:
                    self.items.append(1)

            def scrape(self):
                return sum(1 for x in self.items)
    """, select={"RL004"})
    assert len(fs) == 1
    assert fs[0].func == "Engine.scrape"


@pytest.mark.parametrize("read", [
    "list(self.items)",
    "sorted(self.items)",
    "[x for x in self.items]",
    "sum(1 for v in self.items.values())",
], ids=["list", "sorted", "comprehension", "values_view"])
def test_rl004_iteration_forms(tmp_path, read):
    fs = _lint(tmp_path, f"""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = {{}}

            def step(self):
                with self._lock:
                    self.items.update(a=1)

            def scrape(self):
                return {read}
    """, select={"RL004"})
    assert len(fs) == 1


def test_rl004_locked_iteration_clean(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def step(self):
                with self._lock:
                    self.items.append(1)

            def scrape(self):
                with self._lock:
                    return list(self.items)
    """, select={"RL004"})
    assert fs == []


def test_rl004_unlocked_mutations_not_flagged(tmp_path):
    """If no mutation is locked there is no lock discipline to
    enforce — that's RL001 territory, not RL004."""
    fs = _lint(tmp_path, """
        class Bag:
            def __init__(self):
                self.items = []

            def put(self, x):
                self.items.append(x)

            def scan(self):
                return list(self.items)
    """, select={"RL004"})
    assert fs == []


def test_rl004_annassign_and_comprehension_containers(tmp_path):
    """Annotated (`self.x: List[int] = []`) and comprehension-built
    containers are tracked too — the engine builds its slot table
    with a list comprehension."""
    fs = _lint(tmp_path, """
        import threading
        from typing import List

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.waiting: List[int] = []
                self.slots = [object() for _ in range(4)]

            def step(self):
                with self._lock:
                    self.waiting.append(1)

            def scrape(self):
                return [w for w in self.waiting]
    """, select={"RL004"})
    assert len(fs) == 1
    assert "waiting" in fs[0].detail


# ------------------------------------------------------------------ RL005

def test_rl005_untracked_thread(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Pump:
            def start(self):
                t = threading.Thread(target=self._run)
                t.start()
    """, select={"RL005"})
    assert len(fs) == 1
    assert "t" in fs[0].detail


@pytest.mark.parametrize("src", [
    """
    import threading

    class Pump:
        def start(self):
            t = threading.Thread(target=self._run, daemon=True)
            t.start()
    """,
    """
    import threading

    class Pump:
        def start(self):
            t = threading.Thread(target=self._run)
            t.start()
            t.join()
    """,
    """
    import threading

    class Pump:
        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def close(self):
            self._t.join()
    """,
], ids=["daemon_kwarg", "local_join", "field_joined_elsewhere"])
def test_rl005_tracked_threads_clean(tmp_path, src):
    assert _lint(tmp_path, src, select={"RL005"}) == []


def test_rl005_module_level_function(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
    """, select={"RL005"})
    assert len(fs) == 1


# ------------------------------------------------------------------ RL006

def test_rl006_sibling_deadlock(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def stats(self):
                with self._lock:
                    return 1

            def snapshot(self):
                with self._lock:
                    return self.stats()
    """, select={"RL006"})
    assert len(fs) == 1
    assert "deadlock" in fs[0].detail


def test_rl006_reacquire_nonreentrant(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """, select={"RL006"})
    assert len(fs) == 1
    assert "reacquire" in fs[0].detail


def test_rl006_rlock_reentry_clean(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.RLock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """, select={"RL006"})
    assert fs == []


def test_rl006_callback_under_lock(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.alert_hook = None

            def f(self):
                with self._lock:
                    self.alert_hook()
    """, select={"RL006"})
    assert len(fs) == 1
    assert "callback" in fs[0].detail


def test_rl006_statically_known_listener_clean(tmp_path):
    """`self.telemetry.on_tick(...)` is a statically-known listener
    method, not a configurable callable — only *_hook/*_callback/_cb
    tails count for dotted calls (the engine telemetry surface would
    otherwise drown the rule)."""
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    self.telemetry.on_tick(1)
    """, select={"RL006"})
    assert fs == []


# ------------------------------------------- suppressions + CLI plumbing

def test_inline_disable_comment(tmp_path):
    fs = _lint(tmp_path, """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def step(self):
                with self._lock:
                    self.items.append(1)

            def scrape(self):
                return list(self.items)  # racelint: disable=RL004 -- lock-free by contract
    """, select={"RL004"})
    assert fs == []


def test_noqa_comment(tmp_path):
    fs = _lint(tmp_path, """
        import time

        class S:
            async def h(self):
                time.sleep(1)  # noqa: RL002
    """, select={"RL002"})
    assert fs == []


def _cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tools.racelint", *args],
        cwd=str(cwd), capture_output=True, text=True)


VIOLATION = """
import time

class S:
    async def h(self):
        time.sleep(1)
"""


def test_cli_seeded_violation_exits_nonzero(tmp_path):
    (tmp_path / "bad.py").write_text(VIOLATION)
    r = _cli([str(tmp_path / "bad.py"), "--root", str(tmp_path)], REPO)
    assert r.returncode == 1
    assert "RL002" in r.stdout


def test_cli_fix_baseline_roundtrip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(VIOLATION)
    base = tmp_path / "baseline.json"
    r = _cli([str(bad), "--root", str(tmp_path),
              "--baseline", str(base), "--fix-baseline"], REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    entries = json.loads(base.read_text())["entries"]
    assert len(entries) == 1
    # baselined -> clean; keys are line-independent, so adding a
    # leading comment must not invalidate the entry
    bad.write_text("# moved\n" + VIOLATION)
    r = _cli([str(bad), "--root", str(tmp_path),
              "--baseline", str(base)], REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in (r.stdout + r.stderr)


# --------------------------------------------------------- repo gates

def test_rule_catalogue_complete():
    assert len(ALL_RULES) >= 6
    assert ALL_RULES == tuple(f"RL{i:03d}" for i in range(1, 7))


def test_shipped_baseline_small_and_justified():
    base = load_baseline(str(REPO / "tools" / "racelint" /
                             "baseline.json"))
    assert 0 < len(base.entries) <= 12
    data = json.loads(
        (REPO / "tools" / "racelint" / "baseline.json").read_text())
    for e in data["entries"]:
        just = e.get("justification", "")
        assert just and "TODO" not in just, \
            f"unjustified baseline entry: {e['key']}"


def test_repo_clean_against_shipped_baseline():
    r = _cli(["ray_tpu", "--baseline", "tools/racelint/baseline.json"],
             REPO)
    assert r.returncode == 0, r.stdout + r.stderr


def test_llm_and_serving_planes_zero_baseline():
    """The engine + serving-LLM planes hold a stricter bar: clean
    with NO baseline at all (every finding there was fixed, or
    carries an inline justified suppression)."""
    fs = analyze_paths([str(REPO / "ray_tpu" / "llm" / "_internal"),
                        str(REPO / "ray_tpu" / "serve" / "llm")],
                       root=str(REPO))
    assert fs == [], "\n".join(f.render() for f in fs)
    # the ISSUE 20 traffic recorder lives on the dispatch hot path
    # inside that zero-baseline package: its lock discipline (metric
    # publication outside the lock, one lock per recorder) is gated
    # here, not baselined away
    assert (REPO / "ray_tpu" / "serve" / "llm"
            / "trafficlog.py").exists()


def test_replay_tooling_zero_baseline():
    """ISSUE 20: the replay/lint tooling is host-side stdlib code —
    racelint-clean with no baseline, like the serving planes."""
    fs = analyze_paths([str(REPO / "tools" / "tracereplay"),
                        str(REPO / "tools" / "lint")],
                       root=str(REPO))
    assert fs == [], "\n".join(f.render() for f in fs)
