"""Host-side actor collectives + in-mesh XLA collectives.

Modeled on python/ray/util/collective tests; the XLA path runs under
shard_map on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.ops.jax_compat import shard_map_compat
from ray_tpu.parallel import MeshSpec
from ray_tpu.util.collective import xla as cx


@ray_tpu.remote
class CollectiveWorker:
    def __init__(self, rank, world):
        self.rank = rank
        self.world = world

    def setup(self, group):
        from ray_tpu.util import collective as col
        col.init_collective_group(self.world, self.rank, group_name=group)
        return True

    def do_allreduce(self, group):
        from ray_tpu.util import collective as col
        return col.allreduce(np.full((4,), float(self.rank + 1)),
                             group_name=group)

    def do_allgather(self, group):
        from ray_tpu.util import collective as col
        return col.allgather(self.rank * 10, group_name=group)

    def do_broadcast(self, group):
        from ray_tpu.util import collective as col
        return col.broadcast(f"from-{self.rank}", src_rank=2,
                             group_name=group)

    def do_sendrecv(self, group):
        from ray_tpu.util import collective as col
        if self.rank == 0:
            col.send({"x": 42}, dst_rank=1, group_name=group)
            return None
        elif self.rank == 1:
            return col.recv(src_rank=0, group_name=group)
        return None


def test_host_collectives(ray_start):
    world = 3
    workers = [CollectiveWorker.remote(r, world) for r in range(world)]
    assert all(ray_tpu.get([w.setup.remote("g1") for w in workers],
                           timeout=120))

    sums = ray_tpu.get([w.do_allreduce.remote("g1") for w in workers],
                       timeout=120)
    for s in sums:
        np.testing.assert_allclose(s, np.full((4,), 6.0))  # 1+2+3

    gathered = ray_tpu.get([w.do_allgather.remote("g1") for w in workers],
                           timeout=120)
    assert all(g == [0, 10, 20] for g in gathered)

    bcast = ray_tpu.get([w.do_broadcast.remote("g1") for w in workers],
                        timeout=120)
    assert bcast == ["from-2"] * world

    out = ray_tpu.get([w.do_sendrecv.remote("g1") for w in workers],
                      timeout=120)
    assert out[1] == {"x": 42}


def test_xla_collectives_in_mesh():
    mesh = MeshSpec(dp=8, fsdp=1, sp=1, tp=1).build()

    def fn(x):
        total = cx.allreduce(x, "dp")
        gathered = cx.allgather(x, "dp", axis=0)
        rank_val = cx.broadcast(x * 0 + cx.rank("dp").astype(x.dtype), "dp",
                                src_rank=3)
        return total, gathered, rank_val

    sharded = shard_map_compat(
        fn, mesh,
        in_specs=jax.sharding.PartitionSpec("dp"),
        out_specs=(jax.sharding.PartitionSpec("dp"),
                   jax.sharding.PartitionSpec("dp"),
                   jax.sharding.PartitionSpec("dp")))
    x = jnp.arange(8, dtype=jnp.float32)
    total, gathered, rank_val = sharded(x)
    np.testing.assert_allclose(np.asarray(total), np.full((8,), 28.0))
    np.testing.assert_allclose(np.asarray(rank_val), np.full((8,), 3.0))


def test_xla_reducescatter():
    mesh = MeshSpec(dp=4, fsdp=1, sp=1, tp=1).build(jax.devices()[:4])

    def fn(x):
        return cx.reducescatter(x, "dp", axis=0)

    sharded = shard_map_compat(
        fn, mesh,
        in_specs=jax.sharding.PartitionSpec(),
        out_specs=jax.sharding.PartitionSpec("dp"))
    x = jnp.ones((8, 2), jnp.float32)
    out = sharded(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 4.0))
