"""JaxTrainer: worker groups, reporting, checkpointing, failure recovery.

Modeled on python/ray/train/tests + v2 controller tests."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


def test_single_worker_reports(ray_start, tmp_path):
    def loop(config):
        for step in range(3):
            train.report({"step": step, "loss": 1.0 / (step + 1)})

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_dataframe) == 3


def test_multi_worker_context(ray_start, tmp_path):
    def loop():
        ctx = train.get_context()
        train.report({"rank": ctx.world_rank, "world": ctx.world_size})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=3),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    # history holds rank 0's reports only
    assert result.metrics == {"rank": 0, "world": 3}


def test_checkpointing_and_retention(ray_start, tmp_path):
    def loop(config):
        import tempfile
        for step in range(4):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "model.txt"), "w") as f:
                f.write(f"weights-{step}")
            train.report({"step": step, "score": float(step)},
                         checkpoint=Checkpoint.from_directory(d))

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t3", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score")))
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    with open(os.path.join(result.checkpoint.path, "model.txt")) as f:
        assert f.read() == "weights-3"
    ckpt_dirs = [d for d in os.listdir(os.path.join(str(tmp_path), "t3"))
                 if d.startswith("checkpoint_")]
    assert len(ckpt_dirs) == 2


def test_failure_restart_resumes_from_checkpoint(ray_start, tmp_path):
    marker = str(tmp_path / "crashed_once")

    def loop(config):
        import tempfile
        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            with open(os.path.join(ckpt.as_directory(), "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 4):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "step.txt"), "w") as f:
                f.write(str(step))
            train.report({"step": step},
                         checkpoint=Checkpoint.from_directory(d))
            if step == 1 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("simulated failure at step 1")

    trainer = JaxTrainer(
        loop, train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t4", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    steps = [m["step"] for m in result.metrics_dataframe]
    assert 2 in steps and steps.count(0) == 1, steps


def test_failure_budget_exhausted(ray_start, tmp_path):
    def loop():
        raise ValueError("always fails")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t5", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=0)))
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in str(result.error)


def test_jax_training_loop_single_worker(ray_start, tmp_path):
    """End-to-end: actual jax Llama training inside a train worker."""

    def loop(config):
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.models import llama
        from ray_tpu.models.training import (TrainStepBundle,
                                             default_optimizer)
        from ray_tpu.parallel import MeshSpec

        cfg = llama.config("debug")
        mesh = MeshSpec(dp=1, fsdp=1, sp=1, tp=1).build(jax.devices()[:1])
        bundle = TrainStepBundle(cfg, mesh,
                                 optimizer=default_optimizer(total_steps=10))
        state = bundle.init_state(0)
        tokens = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 128)), jnp.int32)
        for step in range(3):
            state, metrics = bundle.step(state, bundle.shard_batch(tokens))
            train.report({"step": step, "loss": float(metrics["loss"])})

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1, cpus_per_worker=2),
        run_config=RunConfig(name="t6", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert np.isfinite(result.metrics["loss"])


def test_elastic_trainer_runs_with_available_workers(ray_start):
    """ScalingConfig(min_workers=...) runs with the largest placeable gang
    instead of blocking on the full one (Train v2 ScalingPolicy parity)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train import session as train_session

    def loop(config=None):
        from ray_tpu.train import session
        ctx = session.get_context()
        session.report({"world_size": ctx.world_size, "loss": 1.0})

    # the 8-CPU test cluster cannot place 64 x 1-CPU workers; elastic
    # shrinks until the gang fits
    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=64, cpus_per_worker=1.0,
                                     min_workers=1),
        run_config=RunConfig(name="elastic-test"))
    result = trainer.fit()
    assert result.error is None, result.error
    assert 1 <= result.metrics["world_size"] < 64


def test_multihost_jax_distributed_train(ray_start):
    """The DCN path (VERDICT r1 weak #8): two TrainWorker processes
    federate one jax runtime via jax.distributed (rank 0 hosts the
    coordination service on its own node) and run a genuinely
    cross-process sharded computation."""
    import ray_tpu.train as train
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        ctx = train.get_context()
        rank = ctx.world_rank
        mesh = Mesh(np.array(jax.devices()), ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        # each process contributes rank+1; the global sum proves the
        # reduction crossed the process boundary
        local = jnp.ones((jax.local_device_count(), 4)) * (rank + 1)
        arr = jax.make_array_from_process_local_data(sh, local)
        total = jax.jit(lambda a: a.sum(),
                        out_shardings=NamedSharding(mesh, P()))(arr)
        train.report({"total": float(total),
                      "processes": jax.process_count(),
                      "global_devices": jax.device_count()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        bootstrap_jax_distributed=True)
    result = trainer.fit()
    assert result.error is None, result.error
    m = result.metrics
    # 2 processes x 8 virtual local devices = 16 global; each of the 8
    # local rows of 4 contributes rank+1: 8*4*1 + 8*4*2 = 96
    assert m["global_devices"] == 16, m
    assert m["processes"] == 2, m
    assert m["total"] == 96.0, m
