"""Actor concurrency groups + worker-log streaming to the driver."""

import sys
import time

import pytest

import ray_tpu


def test_concurrency_group_isolates_blocked_default_group(ray_start):
    """An 'io' group method keeps serving while the default (serial)
    group is occupied by a long call (reference parity: core worker
    concurrency groups)."""
    @ray_tpu.remote(concurrency_groups={"io": 2})
    class Worker:
        def slow(self):
            time.sleep(3.0)
            return "slow-done"

        def ping(self):
            return "pong"

    a = Worker.remote()
    ray_tpu.get(a.ping.remote())          # warm the actor
    slow_ref = a.slow.remote()            # occupies the default group
    t0 = time.time()
    out = ray_tpu.get(
        a.ping.options(concurrency_group="io").remote(), timeout=60)
    io_latency = time.time() - t0
    assert out == "pong"
    assert io_latency < 2.0, io_latency   # did NOT wait for slow()
    assert ray_tpu.get(slow_ref, timeout=60) == "slow-done"


def test_unknown_concurrency_group_errors(ray_start):
    @ray_tpu.remote(concurrency_groups={"io": 1})
    class A:
        def m(self):
            return 1

    a = A.remote()
    with pytest.raises(Exception, match="concurrency group"):
        ray_tpu.get(a.m.options(concurrency_group="nope").remote(),
                    timeout=60)


def test_worker_prints_stream_to_driver(ray_start, capfd):
    @ray_tpu.remote
    def chatty():
        print("HELLO_FROM_WORKER_XYZ", flush=True)
        return 1

    assert ray_tpu.get(chatty.remote()) == 1
    # the daemon log pump ticks every 0.5s; wait for the line to arrive
    deadline = time.time() + 30
    while time.time() < deadline:
        err = capfd.readouterr().err
        if "HELLO_FROM_WORKER_XYZ" in err:
            assert "(worker pid=" in err
            return
        time.sleep(0.3)
    raise AssertionError("worker print never reached the driver stderr")


def test_profiling_stacks_and_memory(ray_start):
    from ray_tpu.util.profiling import dump_stacks, memory_summary

    stacks = dump_stacks()
    assert "thread" in stacks and "test_profiling" in stacks
    mem = memory_summary()
    assert mem["rss_bytes"] and mem["rss_bytes"] > 1 << 20


def test_dashboard_ui_and_profile_endpoint(ray_start):
    import json
    import urllib.request

    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.dashboard.head import stop_dashboard

    dash = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{dash.port}"
        html = urllib.request.urlopen(
            f"{base}/", timeout=30).read().decode()
        assert "<html" in html.lower() and "ray_tpu" in html
        status = json.loads(urllib.request.urlopen(
            f"{base}/api/cluster_status", timeout=30).read())
        assert status["nodes_alive"] >= 1
        prof = json.loads(urllib.request.urlopen(
            f"{base}/api/profile/stacks", timeout=60).read())
        assert prof["nodes"], prof
        assert "daemon" in prof["nodes"][0]["stacks"]
    finally:
        # the dashboard is a process-wide singleton: leaving it up would
        # hijack later tests' fixed-port start_dashboard calls
        stop_dashboard()


def test_config_registry():
    from ray_tpu._private.config import RayTpuConfig, get_config

    cfg = get_config()
    assert cfg.fetch_chunk_bytes > 0
    assert 0 < cfg.arena_spill_low < cfg.arena_spill_high <= 1.0
    assert isinstance(cfg, RayTpuConfig)


def test_chaos_utils():
    import ray_tpu
    from ray_tpu.util import chaos

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    @ray_tpu.remote(max_restarts=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    a = Counter.remote()
    assert ray_tpu.get(a.incr.remote()) == 1
    assert chaos.kill_actor_worker(a) is True
    # actor restarts (state resets — fresh instance)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            assert ray_tpu.get(a.incr.remote(), timeout=60) >= 1
            break
        except Exception:
            time.sleep(0.5)
    else:
        raise AssertionError("actor never came back after chaos kill")
    assert chaos.list_worker_pids()


def test_rpc_chaos_injection(ray_start):
    from ray_tpu._private import state
    from ray_tpu._private.protocol import ConnectionLost
    from ray_tpu.util.chaos import RpcChaos

    client = state.current_client()

    async def probe():
        return await client._controller().call("list_nodes")

    with RpcChaos(failure_rate=1.0, seed=0):
        with pytest.raises(ConnectionLost):
            client.loop_runner.run_sync(probe())
    # restored after the context exits
    assert client.loop_runner.run_sync(probe())


def test_multiprocessing_pool_shim(ray_start):
    from ray_tpu.util.multiprocessing import Pool

    def sq(x):
        return x * x

    def add(a, b):
        return a + b

    with Pool(processes=2) as pool:
        assert pool.map(sq, range(6)) == [0, 1, 4, 9, 16, 25]
        assert pool.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(add, (5, 6)) == 11
        r = pool.map_async(sq, [2, 3])
        assert r.get(timeout=60) == [4, 9]
        assert sorted(pool.imap_unordered(sq, [1, 2, 3])) == [1, 4, 9]
