"""C++ worker API: build the native client and drive it end-to-end
against a live cluster (reference parity: cpp/ — the standalone C++ Ray
API; ours speaks the frame protocol directly and submits tasks by
cross-language function descriptor)."""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "build", "ray_demo")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_cpp_api_end_to_end(ray_start):
    build = subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                           capture_output=True, text=True, timeout=180)
    assert build.returncode == 0, build.stderr[-2000:]

    from ray_tpu._private import worker
    rt = worker._runtime
    addr = f"{rt.controller.address[0]}:{rt.controller.address[1]}"
    run = subprocess.run([DEMO, addr], capture_output=True, text=True,
                         timeout=180)
    assert "CPP_API_ALL_OK" in run.stdout, (run.stdout, run.stderr[-2000:])


def _descriptor_spec(client, module, name, args):
    from ray_tpu._private.ids import ObjectID, TaskID
    from ray_tpu._private.serialization import serialize

    rid = ObjectID.generate().hex()
    client.ref_counter.register_owned(rid)
    return rid, {
        "task_id": TaskID.generate().hex(),
        "name": f"{module}.{name}",
        "fn_desc": {"module": module, "name": name},
        "args_blob": serialize((tuple(args), {})).to_flat(),
        "return_id": rid, "return_ids": [rid], "num_returns": 1,
        "owner_addr": client.address,
        "resources": {"CPU": 1.0},
        "scheduling": None, "is_actor_creation": False,
        "runtime_env": None, "max_retries": 0,
    }


def test_descriptor_tasks_from_python(ray_start):
    """The exact spec shape the C++ API submits — fn_desc instead of
    code — executes on Python workers, including a dotted qualname that
    exercises the getattr walk."""
    import ray_tpu
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.state import current_client

    client = current_client()
    # dotted MODULE (importlib path)
    rid, spec = _descriptor_spec(client, "os.path", "join", ["a", "b"])
    client.controller_rpc("submit_task", spec=spec)
    assert ray_tpu.get(ObjectRef(rid, client.address,
                                 _client=client), timeout=60) == "a/b"

    # dotted QUALNAME (attribute walk: module os, name path.join)
    rid2, spec2 = _descriptor_spec(client, "os", "path.join", ["x", "y"])
    client.controller_rpc("submit_task", spec=spec2)
    assert ray_tpu.get(ObjectRef(rid2, client.address,
                                 _client=client), timeout=60) == "x/y"
