"""Llama model + sharded training on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.models.training import TrainStepBundle, default_optimizer
from ray_tpu.parallel import MeshSpec


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(0, 256, (8, 256)), jnp.int32)


def _bundle(mesh_spec, **cfg_overrides):
    cfg = llama.config("debug", **cfg_overrides)
    mesh = mesh_spec.build()
    return TrainStepBundle(cfg, mesh,
                           optimizer=default_optimizer(total_steps=100))


def test_forward_shapes():
    cfg = llama.config("debug")
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.zeros((2, 64), jnp.int32)
    logits = llama.forward(cfg, params, toks)
    assert logits.shape == (2, 64, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_fsdp_tp_training_loss_decreases(tokens):
    bundle = _bundle(MeshSpec(dp=2, fsdp=2, sp=1, tp=2))
    state = bundle.init_state(0)
    batch = bundle.shard_batch(tokens)
    losses = []
    for _ in range(5):
        state, metrics = bundle.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses[-1])


def test_param_shardings_applied(tokens):
    bundle = _bundle(MeshSpec(dp=1, fsdp=4, sp=1, tp=2))
    state = bundle.init_state(0)
    wq = state[0]["layers"]["wq"]
    spec = wq.sharding.spec
    # layer dim rides the pp axis (size 1 here — replicated; stage-sharded
    # once the mesh has pp > 1)
    assert spec == jax.sharding.PartitionSpec("pp", "fsdp", "tp"), spec


def test_sp_ring_matches_dense(tokens):
    dense = _bundle(MeshSpec(dp=2, fsdp=2, sp=1, tp=2))
    ring = _bundle(MeshSpec(dp=1, fsdp=2, sp=4, tp=1))
    s1 = dense.init_state(0)
    s2 = ring.init_state(0)
    _, m1 = dense.step(s1, dense.shard_batch(tokens))
    _, m2 = ring.step(s2, ring.shard_batch(tokens))
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 0.05


def test_gqa_heads_config():
    cfg = llama.config("debug", n_heads=4, n_kv_heads=1)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    logits = llama.forward(cfg, params, jnp.zeros((1, 32), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


def test_num_params_8b_close():
    cfg = llama.config("8b")
    n = cfg.num_params()
    assert 7.5e9 < n < 8.5e9, n


def test_chunked_loss_matches_unchunked():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models import llama

    cfg_c = llama.config("debug", loss_chunk=64)
    cfg_u = llama.config("debug", loss_chunk=0)
    params = llama.init_params(cfg_u, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg_u.vocab_size, (2, 256)),
        jnp.int32)
    mask = jnp.asarray(
        np.random.default_rng(1).integers(0, 2, (2, 256)), jnp.int32)

    for m in (None, mask):
        (lc, _), gc = jax.value_and_grad(
            lambda p: llama.loss_fn(cfg_c, p, tokens, mask=m),
            has_aux=True)(params)
        (lu, _), gu = jax.value_and_grad(
            lambda p: llama.loss_fn(cfg_u, p, tokens, mask=m),
            has_aux=True)(params)
        assert jnp.allclose(lc, lu, atol=1e-5)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), gc, gu)
        assert max(jax.tree.leaves(diffs)) < 1e-3


def test_chunked_loss_awkward_seq_length():
    # seq 192 with loss_chunk 128 -> largest divisor 96 is used; must not
    # silently fall back to full-vocab logits nor error
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models import llama

    cfg = llama.config("debug", loss_chunk=128, max_seq=512)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 192)),
        jnp.int32)
    loss, metrics = llama.loss_fn(cfg, params, tokens)
    assert bool(jnp.isfinite(loss))
