"""State API, CLI surface, metrics, ActorPool/Queue, jobs, dashboard.

Modeled on the reference's python/ray/tests/test_state_api*.py,
test_actor_pool.py, test_queue.py, test_metrics_agent.py, and
dashboard/modules/job/tests.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Empty, Queue, metrics, state, tracing
from ray_tpu.util.check_serialize import inspect_serializability


# ---------------------------------------------------------------- state API

def test_state_api_tasks_and_actors(ray_start):
    @ray_tpu.remote
    def named_task(x):
        return x + 1

    @ray_tpu.remote
    class StateActor:
        def ping(self):
            return "pong"

    refs = [named_task.remote(i) for i in range(3)]
    actor = StateActor.remote()
    ray_tpu.get(refs + [actor.ping.remote()])

    # FINISHED lands asynchronously after the result — poll briefly
    deadline = time.time() + 10
    finished = []
    while time.time() < deadline and len(finished) < 3:
        finished = [t for t in state.list_tasks()
                    if t["name"].startswith("named_task")
                    and t["state"] == "FINISHED"]
        time.sleep(0.1)
    assert len(finished) >= 3
    assert all(t["start_time"] is not None for t in finished)

    actors = state.list_actors()
    assert any(a.get("class_name") == "StateActor" for a in actors)

    summary = state.summarize_tasks()
    assert summary["total"] >= 3
    assert "FINISHED" in summary["by_state"]
    assert state.summarize_actors()["total"] >= 1


def test_state_api_objects(ray_start):
    import numpy as np

    ref = ray_tpu.put(np.zeros(1 << 18, dtype=np.float64))  # 2MB
    ray_tpu.get(ref)
    objs = state.list_objects()
    assert any(o["size"] > 1 << 20 for o in objs)
    assert state.summarize_objects()["total"] >= 1


def test_state_api_task_failure_recorded(ray_start):
    @ray_tpu.remote
    def fail_on_purpose():
        raise RuntimeError("nope")

    with pytest.raises(Exception):
        ray_tpu.get(fail_on_purpose.remote())
    time.sleep(0.3)
    tasks = state.list_tasks()
    ours = [t for t in tasks if t["name"].startswith("fail_on_purpose")]
    # execution errors surface via the result path; the controller table
    # still records the task reaching RUNNING
    assert ours and ours[0]["state"] in ("RUNNING", "FINISHED", "FAILED")


# ---------------------------------------------------------------- metrics

def test_metrics_counter_gauge_histogram_prometheus():
    c = metrics.Counter("reqs_total", "requests", ("route",))
    c.inc(3, {"route": "/a"})
    c.inc(1, {"route": "/b"})
    g = metrics.Gauge("inflight", "", ())
    g.set(7)
    h = metrics.Histogram("lat_s", "", [0.1, 1.0], ())
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = metrics.export_prometheus()
    assert 'reqs_total{route="/a"} 3.0' in text
    assert "inflight 7.0" in text
    assert 'lat_s_bucket{le="+Inf"} 3' in text
    assert "lat_s_count 3" in text
    with pytest.raises(ValueError):
        c.inc(1)          # missing tag
    with pytest.raises(ValueError):
        c.inc(-1, {"route": "/a"})


def test_metrics_reregistration_merges_not_clobbers():
    """Regression (ISSUE 5 satellite): constructing a second Metric
    with an existing name used to silently replace the registry entry,
    orphaning every prior handle — its writes kept landing on the
    shadowed object and vanished from the exposition. Now the SAME
    instance comes back when type+tags match (both handles' writes
    export), and a mismatched re-registration raises."""
    a = metrics.Counter("rereg_total", "first", ("route",))
    a.inc(2, {"route": "/x"})
    b = metrics.Counter("rereg_total", "second", ("route",))
    assert b is a                         # merged, not clobbered
    b.inc(3, {"route": "/x"})
    text = metrics.export_prometheus()
    assert 'rereg_total{route="/x"} 5.0' in text

    with pytest.raises(ValueError):       # type mismatch
        metrics.Gauge("rereg_total", "", ("route",))
    with pytest.raises(ValueError):       # tag-key mismatch
        metrics.Counter("rereg_total", "", ("other",))

    h1 = metrics.Histogram("rereg_h", "", [0.1, 1.0], ())
    h1.observe(0.5)
    h2 = metrics.Histogram("rereg_h", "", [1.0, 0.1], ())  # same sorted
    assert h2 is h1
    h2.observe(0.05)
    text = metrics.export_prometheus()
    assert "rereg_h_count 2" in text
    with pytest.raises(ValueError):       # boundary mismatch
        metrics.Histogram("rereg_h", "", [0.2, 2.0], ())


def test_metrics_merge_expositions():
    """Regression (ISSUE 5 review): /metrics must not concatenate
    replica expositions verbatim — in-process replicas render the
    SAME process registry, so naive joining repeats every series
    (Prometheus rejects duplicate samples), and even distinct blocks
    repeat # HELP/# TYPE family headers. merge_expositions collapses
    duplicate sample lines and keeps one header pair per family."""
    block = ("# HELP m_total things\n"
             "# TYPE m_total counter\n"
             'm_total{model="a"} 3.0\n')
    # two replicas sharing one registry → identical blocks → one copy
    merged = metrics.merge_expositions([block, block])
    assert merged.count('m_total{model="a"} 3.0') == 1
    assert merged.count("# TYPE m_total counter") == 1
    # distinct processes: same family, different samples → one header,
    # both samples grouped under it (contiguous, as the format requires)
    other = ("# HELP m_total things\n"
             "# TYPE m_total counter\n"
             'm_total{model="b"} 7.0\n')
    merged = metrics.merge_expositions([block, other])
    assert merged.count("# TYPE m_total counter") == 1
    assert 'm_total{model="a"} 3.0' in merged
    assert 'm_total{model="b"} 7.0' in merged
    # a live counter can advance BETWEEN two renders of one shared
    # registry: dedup keys on series identity, not line text — one
    # line survives (first value), not two conflicting samples
    drift = block.replace(" 3.0", " 4.0")
    merged = metrics.merge_expositions([block, drift])
    assert merged.count('m_total{model="a"}') == 1
    assert 'm_total{model="a"} 3.0' in merged


def test_metrics_flush_and_collect(ray_start):
    c = metrics.Counter("flush_test_total", "", ())
    c.inc(5)
    metrics.flush_to_kv()
    cluster = metrics.collect_cluster()
    assert any("flush_test_total" in snap["metrics"]
               for snap in cluster.values())


# ---------------------------------------------------------------- tracing

def test_tracing_spans_and_chrome_export(ray_start):
    tracing.clear()
    tracing.enable()
    try:
        with tracing.span("driver_work", "custom", foo="bar"):
            time.sleep(0.01)
        events = tracing.get_events()
        assert any(e["name"] == "driver_work" and e["args"]["foo"] == "bar"
                   and e["dur"] >= 10_000 for e in events)
        doc = json.loads(tracing.export_chrome_trace())
        assert doc["traceEvents"]
    finally:
        tracing.disable()
        tracing.clear()


# ---------------------------------------------------------------- pool/queue

def test_actor_pool_ordered_and_unordered(ray_start):
    @ray_tpu.remote
    class PoolWorker:
        def work(self, x):
            return x * 10

    pool = ActorPool([PoolWorker.remote() for _ in range(2)])
    results = list(pool.map(lambda a, v: a.work.remote(v), range(6)))
    assert results == [0, 10, 20, 30, 40, 50]
    unordered = sorted(pool.map_unordered(
        lambda a, v: a.work.remote(v), range(6)))
    assert unordered == [0, 10, 20, 30, 40, 50]


def test_queue_fifo_and_timeout(ray_start):
    q = Queue(maxsize=4)
    for i in range(4):
        q.put(i)
    assert q.qsize() == 4 and q.full()
    assert [q.get() for _ in range(4)] == [0, 1, 2, 3]
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


# ---------------------------------------------------------------- serialize

def test_inspect_serializability():
    ok, fails = inspect_serializability(lambda x: x + 1)
    assert ok and not fails
    import threading
    lock = threading.Lock()

    def closure():
        return lock

    ok, fails = inspect_serializability(closure)
    assert not ok
    assert any("lock" in f.name for f in fails)


# ---------------------------------------------------------------- kv

def test_internal_kv(ray_start):
    from ray_tpu.experimental import internal_kv as kv

    assert kv._internal_kv_initialized()
    kv._internal_kv_put("k1", b"v1")
    assert kv._internal_kv_get("k1") == b"v1"
    assert kv._internal_kv_exists("k1")
    kv._internal_kv_put("ns_key", b"x", namespace="myns")
    assert kv._internal_kv_get("ns_key", namespace="myns") == b"x"
    assert any(b"k1" in k for k in kv._internal_kv_list("k"))
    assert kv._internal_kv_del("k1")
    assert not kv._internal_kv_exists("k1")


# ---------------------------------------------------------------- dashboard

def test_dashboard_serve_endpoint_and_ui_tabs(ray_start):
    """Round-5 UI upgrade: /api/serve endpoint + serve/metrics tabs,
    sortable/filterable tables (single-file SPA — no build step by
    design; the reference ships a React app)."""
    import urllib.request
    import json as _json
    from ray_tpu.dashboard import start_dashboard
    dash = start_dashboard(port=0)
    base = f"http://127.0.0.1:{dash.port}"
    with urllib.request.urlopen(base + "/api/serve", timeout=15) as r:
        data = _json.loads(r.read())
    assert "applications" in data
    with urllib.request.urlopen(base + "/", timeout=15) as r:
        html = r.read().decode()
    for needle in ('"serve"', '"metrics"', "sortBy", "setFilter",
                   "spark("):
        assert needle in html, needle



def test_dashboard_and_job_submission(ray_start):
    import requests

    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.dashboard.head import stop_dashboard
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    dash = start_dashboard(port=0)   # dynamic: a fixed port can race
    try:                              # parallel sessions on this box
        base = f"http://127.0.0.1:{dash.port}"
        r = requests.get(f"{base}/api/cluster_status", timeout=15)
        assert r.status_code == 200 and r.json()["num_nodes"] >= 1
        r = requests.get(f"{base}/api/nodes", timeout=15)
        assert r.status_code == 200 and len(r.json()) >= 1
        r = requests.get(f"{base}/metrics", timeout=15)
        assert r.status_code == 200

        client = JobSubmissionClient(base)
        job_id = client.submit_job(
            entrypoint="python -c \"print('job says hi')\"")
        status = client.wait_until_finished(job_id, timeout_s=60)
        assert status == JobStatus.SUCCEEDED
        assert "job says hi" in client.get_job_logs(job_id)

        bad = client.submit_job(entrypoint="python -c 'import sys; "
                                           "sys.exit(3)'")
        assert client.wait_until_finished(bad, 60) == JobStatus.FAILED

        slow = client.submit_job(entrypoint="sleep 60")
        deadline = time.time() + 20
        while (client.get_job_status(slow) == JobStatus.PENDING
               and time.time() < deadline):
            time.sleep(0.2)
        assert client.stop_job(slow)
        assert client.wait_until_finished(slow, 30) == JobStatus.STOPPED
    finally:
        stop_dashboard()


# ---------------------------------------------------------------- attach

def test_init_address_attach():
    """A second process attaches to this cluster via init(address=...)."""
    import os
    import subprocess
    import sys

    code = """
import ray_tpu
rt = ray_tpu.init(num_cpus=2)
addr = f"{rt.controller.address[0]}:{rt.controller.address[1]}"
import subprocess, sys
# both accepted forms: bare host:port and the ray:// client-scheme alias
for prefix in ("", "ray://"):
    child = subprocess.run(
        [sys.executable, "-c", f'''
import ray_tpu
ray_tpu.init(address={prefix!r} + {addr!r})

@ray_tpu.remote
def f(x):
    return x * 3

assert ray_tpu.get(f.remote(14)) == 42
print("ATTACH_OK")
ray_tpu.shutdown()
'''], capture_output=True, text=True, timeout=120)
    sys.stdout.write(child.stdout)
    sys.stderr.write(child.stderr[-2000:])
ray_tpu.shutdown()
"""
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    assert out.stdout.count("ATTACH_OK") == 2, (out.stdout,
                                                out.stderr[-2000:])


def test_metrics_plane_node_gauges_timeline_grafana(ray_start, tmp_path):
    """Metrics-plane depth (VERDICT r3 missing #8 / weak #6): per-node
    gauges on /metrics, chrome-trace timeline endpoint, Grafana +
    Prometheus config generation."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.dashboard.head import stop_dashboard
    from ray_tpu.dashboard.metrics_config import write_metrics_configs

    @ray_tpu.remote
    def work(x):
        return x * 2

    assert ray_tpu.get([work.remote(i) for i in range(4)]) == [0, 2, 4, 6]

    dash = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{dash.port}"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=30).read().decode()
        assert "ray_tpu_node_workers{" in text
        assert "ray_tpu_node_arena_pressure{" in text
        assert 'ray_tpu_node_resource_total{node_id=' in text
        # native C++ arena counters flow through gossip into the gauges
        assert "ray_tpu_node_arena_allocs{" in text
        assert "ray_tpu_node_arena_crash_sweeps{" in text

        tl = _json.loads(urllib.request.urlopen(
            f"{base}/api/timeline", timeout=30).read())
        events = tl["traceEvents"]
        assert any(e["name"] == "work" and e["ph"] == "X"
                   for e in events), events[:3]
        assert all(e["dur"] > 0 for e in events)
    finally:
        stop_dashboard()

    arts = write_metrics_configs(str(tmp_path), "127.0.0.1:9999")
    prom = open(arts["prometheus"]).read()
    assert "file_sd_configs" in prom
    sd = _json.loads(open(arts["service_discovery"]).read())
    assert sd[0]["targets"] == ["127.0.0.1:9999"]
    dashboard = _json.loads(open(arts["grafana_dashboard"]).read())
    panel_exprs = [t["expr"] for p in dashboard["panels"]
                   for t in p["targets"]]
    assert any("arena_pressure" in e for e in panel_exprs)
    assert open(arts["grafana_datasource"]).read().startswith("apiVersion")


def test_trace_context_propagates_into_tasks(ray_start):
    """Span context rides the task spec into the worker (reference
    parity: tracing_helper.py:165 _DictPropagator): the worker's
    execute span joins the driver's trace, and the user fn sees the
    ambient context."""
    import time as _time

    import ray_tpu
    from ray_tpu.util import tracing

    tracing.enable()
    tracing.clear()
    try:
        @ray_tpu.remote(runtime_env={"env_vars": {"RAY_TPU_TRACE": "1"}})
        def traced():
            from ray_tpu.util import tracing as t
            return t.current_context()

        with tracing.span("driver_work") as driver_ctx:
            ref = traced.remote()
        worker_ctx = ray_tpu.get(ref, timeout=60)
        assert worker_ctx is not None, "worker saw no ambient span"
        assert worker_ctx["trace_id"] == driver_ctx["trace_id"]
        assert worker_ctx["span_id"] != driver_ctx["span_id"]
        # the driver side emitted the Perfetto flow-start for the arrow
        evs = tracing.get_events()
        starts = [e for e in evs if e.get("ph") == "s"]
        assert starts, evs
        # cluster assembly: the worker's execute span + flow-finish
        # arrive via the KV ring (flush_to_kv -> collect_cluster), the
        # finish bound to the submission's flow id
        deadline = _time.time() + 15
        finishes = []
        while _time.time() < deadline and not finishes:
            cluster = tracing.collect_cluster()
            finishes = [e for e in cluster if e.get("ph") == "f"]
            _time.sleep(0.2)
        assert finishes, "worker flow-finish never flushed"
        assert {e["id"] for e in finishes} <= {e["id"] for e in starts}
        assert any(e.get("cat") == "task::execute"
                   for e in tracing.collect_cluster())
    finally:
        tracing.disable()
        tracing.clear()


def test_summarize_tasks_duration_stats(ray_start):
    import time as _time

    import ray_tpu
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def napper():
        _time.sleep(0.05)
        return 1

    ray_tpu.get([napper.remote() for _ in range(3)])
    summary = state_api.summarize_tasks()
    group = summary["by_func_name"].get("napper")
    assert group is not None, summary
    assert group["state_counts"].get("FINISHED", 0) >= 3
    dur = group["duration"]
    assert dur and dur["count"] >= 3
    assert dur["mean_s"] >= 0.03, dur


def test_joblib_backend(ray_start):
    """sklearn/joblib Parallel over the cluster (reference parity:
    ray.util.joblib register_ray)."""
    import joblib
    from joblib import Parallel, delayed

    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()

    def work(i):
        import os
        return i * i, os.getpid()

    with joblib.parallel_backend("ray_tpu", n_jobs=4):
        out = Parallel()(delayed(work)(i) for i in range(20))
    vals = [v for v, _ in out]
    pids = {p for _, p in out}
    assert vals == [i * i for i in range(20)]
    # ran in cluster workers, not this process
    import os as _os
    assert _os.getpid() not in pids


def test_pool_apply_async_callbacks(ray_start):
    """std multiprocessing.Pool callback semantics on the shim."""
    import threading

    from ray_tpu.util.multiprocessing import Pool

    done = threading.Event()
    got = []
    with Pool(processes=2) as p:
        p.apply_async(lambda: 21 * 2,
                      callback=lambda r: (got.append(r), done.set()))
        assert done.wait(30)
    assert got == [42]

    errs = []
    edone = threading.Event()

    def boom():
        raise RuntimeError("nope")

    with Pool(processes=2) as p:
        p.apply_async(boom,
                      error_callback=lambda e: (errs.append(e),
                                                edone.set()))
        assert edone.wait(30)
    assert errs and "nope" in str(errs[0])
