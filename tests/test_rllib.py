"""RLlib-equivalent: envs, GAE/vtrace math, PPO/IMPALA learning.

Modeled on the reference's rllib/tests + tuned_examples learning
regression strategy (SURVEY.md §4.5): small learning runs with reward
thresholds, plus exact-math checks against numpy references.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib import (CartPole, IMPALAConfig, Pendulum, PPOConfig,
                           SingleAgentEnvRunner)
from ray_tpu.rllib.algorithms.impala import vtrace
from ray_tpu.rllib.core.postprocessing import compute_gae


# ---------------------------------------------------------------- envs

def test_cartpole_env_shapes_and_termination():
    env = CartPole(max_episode_steps=10)
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    assert obs.shape == (4,)
    done = False
    for _ in range(10):
        state, obs, reward, done = env.step(
            state, jnp.int32(1), key)
        assert reward == 1.0
    assert bool(done)  # truncated at max_episode_steps


def test_pendulum_env():
    env = Pendulum(max_episode_steps=5)
    state, obs = env.reset(jax.random.PRNGKey(1))
    assert obs.shape == (3,)
    state, obs, reward, done = env.step(
        state, jnp.zeros((1,)), jax.random.PRNGKey(2))
    assert float(reward) <= 0.0 and not bool(done)


def test_env_runner_batch_layout():
    r = SingleAgentEnvRunner("CartPole-v1", num_envs=4, rollout_length=16,
                             seed=0)
    out = r.sample()
    b = out["batch"]
    assert b["obs"].shape == (16, 4, 4)
    assert b["actions"].shape == (16, 4)
    assert b["final_vf"].shape == (4,)
    assert out["stats"]["env_steps"] == 64
    # weights round-trip
    w = r.get_weights()
    r.set_weights(w)


# ---------------------------------------------------------------- math

def _gae_numpy(rewards, values, dones, final_values, gamma, lam):
    T, B = rewards.shape
    adv = np.zeros((T, B))
    next_adv = np.zeros(B)
    next_val = final_values
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_val * nd - values[t]
        next_adv = delta + gamma * lam * nd * next_adv
        adv[t] = next_adv
        next_val = values[t]
    return adv, adv + values


def test_gae_matches_numpy_reference():
    rng = np.random.default_rng(0)
    T, B = 12, 3
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = (rng.random((T, B)) < 0.2).astype(np.float32)
    final = rng.normal(size=B).astype(np.float32)
    adv, targets = compute_gae(rewards, values, dones, final,
                               gamma=0.97, lam=0.9)
    ref_adv, ref_t = _gae_numpy(rewards, values, dones, final, 0.97, 0.9)
    np.testing.assert_allclose(np.asarray(adv), ref_adv, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(targets), ref_t, rtol=1e-4,
                               atol=1e-5)


def test_vtrace_on_policy_reduces_to_gae_lambda1():
    """With target==behavior and no clipping active, vtrace vs equals the
    lambda=1 GAE targets (Espeholt et al. 2018, Remark 1)."""
    rng = np.random.default_rng(1)
    T, B = 10, 2
    logp = rng.normal(size=(T, B)).astype(np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    final = rng.normal(size=B).astype(np.float32)
    vs, _ = vtrace(logp, logp, rewards, values, dones, final, gamma=0.95)
    adv, targets = compute_gae(rewards, values, dones, final,
                               gamma=0.95, lam=1.0)
    np.testing.assert_allclose(np.asarray(vs), np.asarray(targets),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------- learning

def test_ppo_learns_cartpole():
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128)
            .training(lr=3e-4, minibatch_size=256, num_epochs=4)
            .debugging(seed=0)
            .build())
    first = algo.train()["episode_return_mean"]
    best = first
    for _ in range(24):
        best = max(best, algo.train()["episode_return_mean"])
        if best > 120:
            break
    assert best > 120, f"PPO failed to learn: first={first} best={best}"
    # checkpoint round-trip
    ckpt = algo.save()
    algo.restore(ckpt)
    algo.stop()


def test_ppo_continuous_pendulum_runs():
    algo = (PPOConfig().environment("Pendulum-v1")
            .env_runners(num_envs_per_env_runner=8,
                         rollout_fragment_length=64)
            .training(minibatch_size=128, num_epochs=2)
            .build())
    m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    algo.stop()


def test_impala_learns_cartpole():
    algo = (IMPALAConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=64)
            .training(lr=2e-3, entropy_coeff=0.005)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(60):
        best = max(best, algo.train()["episode_return_mean"])
        if best > 80:
            break
    assert best > 80, f"IMPALA failed to learn: best={best}"
    algo.stop()


# ---------------------------------------------------------------- distributed

@pytest.mark.usefixtures("ray_start")
def test_ppo_remote_env_runners(ray_start):
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(minibatch_size=64, num_epochs=2)
            .build())
    m = algo.train()
    assert m["num_env_steps_sampled"] == 2 * 4 * 32
    m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    algo.stop()


@pytest.mark.usefixtures("ray_start")
def test_ppo_multi_learner_allreduce(ray_start):
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(minibatch_size=128, num_epochs=1)
            .learners(num_learners=2)
            .build())
    m1 = algo.train()
    m2 = algo.train()
    assert np.isfinite(m2["learner/total_loss"])
    assert m2["num_env_steps_sampled_lifetime"] == 2 * 8 * 32
    algo.stop()


@pytest.mark.usefixtures("ray_start")
def test_impala_async_remote_runners(ray_start):
    algo = (IMPALAConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .build())
    for _ in range(3):
        m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    assert m["num_env_steps_sampled_lifetime"] == 3 * 4 * 32
    algo.stop()


# ---------------------------------------------------------------- tune integration

@pytest.mark.usefixtures("ray_start")
def test_ppo_under_tune(ray_start):
    from ray_tpu import tune
    from ray_tpu.rllib import PPO

    results = tune.Tuner(
        PPO,
        param_space={
            "env": "CartPole-v1",
            "num_envs_per_env_runner": 4,
            "rollout_fragment_length": 16,
            "minibatch_size": 32,
            "num_epochs": 1,
            "lr": tune.grid_search([1e-3, 3e-4]),
        },
        tune_config=tune.TuneConfig(stop={"training_iteration": 2}),
    ).fit()
    assert len(results) == 2
    assert all(np.isfinite(r.metrics["learner/total_loss"])
               for r in results)


# ------------------------------------------------- mean-std obs filter

def test_mean_std_filter_normalizes_and_tracks():
    """Filtered rollouts see ~zero-mean/unit-std obs once the running
    stats converge, and the Welford state matches numpy moments
    (reference parity: connectors/env_to_module/mean_std_filter.py,
    here fused into the compiled rollout)."""
    r = SingleAgentEnvRunner("Pendulum-v1", num_envs=4,
                             rollout_length=64, seed=0,
                             obs_filter="mean_std")
    for _ in range(4):
        out = r.sample()
    count, mean, m2 = r.get_filter_state()
    assert count >= 4 * 64 * 4
    assert mean.shape == (3,)
    # the filter state matches an unfiltered twin's raw-obs moments:
    # same seed + identical policy params => while stats are the
    # identity (first rollout: std=1, mean=0) the trajectories agree,
    # so compare against numpy moments of the twin's FIRST batch
    twin = SingleAgentEnvRunner("Pendulum-v1", num_envs=4,
                                rollout_length=64, seed=0)
    twin.set_weights(r.get_weights())
    raw0 = twin.sample()["batch"]["obs"].reshape(-1, 3)
    r3 = SingleAgentEnvRunner("Pendulum-v1", num_envs=4,
                              rollout_length=64, seed=0,
                              obs_filter="mean_std")
    r3.set_weights(twin.get_weights())
    r3.sample()
    c3, m3, s3 = r3.get_filter_state()
    assert c3 == raw0.shape[0]
    np.testing.assert_allclose(m3, raw0.mean(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(s3 / c3, raw0.var(0),
                               rtol=1e-3, atol=1e-4)
    # normalized obs in the batch are bounded by the clip and centered
    b = out["batch"]["obs"]
    assert np.abs(b).max() <= 10.0
    assert abs(float(b.mean())) < 1.0   # roughly centered after warmup

    # state round-trip
    r2 = SingleAgentEnvRunner("Pendulum-v1", num_envs=4,
                              rollout_length=8, seed=1,
                              obs_filter="mean_std")
    r2.set_filter_state((count, mean, m2))
    c2, mn2, _ = r2.get_filter_state()
    assert c2 == count and np.allclose(mn2, mean)


def test_mean_std_filter_group_merge(ray_start):
    """Remote runners' filter states merge on sync_weights (weighted
    Welford combine) and every runner receives the merged state."""
    import ray_tpu
    from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup

    grp = EnvRunnerGroup("Pendulum-v1", num_env_runners=2,
                         num_envs_per_runner=2, rollout_length=16,
                         obs_filter="mean_std")
    grp.sample()
    grp.sync_weights(grp.get_weights())
    states = ray_tpu.get(
        [r.get_filter_state.remote() for r in grp._remote])
    c0, m0, s0 = states[0]
    c1, m1, s1 = states[1]
    assert c0 == c1 and np.allclose(m0, m1) and np.allclose(s0, s1)
    assert c0 == 2 * 16 * 2        # both runners' obs merged EXACTLY
    #                                once (2 envs x 16 steps x 2
    #                                runners) — full-state re-merging
    #                                would double-count history
    # idempotent: syncing again without sampling must not grow counts
    grp.sync_weights(grp.get_weights())
    states2 = ray_tpu.get(
        [r.get_filter_state.remote() for r in grp._remote])
    assert states2[0][0] == c0
    grp.stop()


def test_ppo_learns_with_obs_filter():
    """The filter must not break learning end-to-end."""
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128,
                         observation_filter="mean_std")
            .training(lr=3e-4, minibatch_size=256, num_epochs=4)
            .debugging(seed=0)
            .build())
    best = -np.inf
    for _ in range(12):
        best = max(best, algo.train()["episode_return_mean"])
        if best > 60:
            break
    assert best > 60, f"filtered PPO failed to improve: best={best}"
    # checkpoint carries the filter state: a restored policy must see
    # obs normalized by the stats it was trained against
    ckpt = algo.save()
    before = algo.env_runner_group.get_filter_state()
    algo.restore(ckpt)
    after = algo.env_runner_group.get_filter_state()
    assert after is not None and after[0] == before[0]
    assert np.allclose(after[1], before[1])
    algo.stop()


def test_impala_async_filter_sync(ray_start):
    """IMPALA's async re-arm path merges per-runner filter deltas into
    the group global (sync_weights never runs on this path)."""
    algo = (IMPALAConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=16,
                         observation_filter="mean_std")
            .training(minibatch_size=64, num_epochs=1)
            .build())
    algo.train()
    algo.train()
    grp = algo.env_runner_group
    assert grp._filter_global is not None
    assert grp._filter_global[0] >= 4 * 16   # at least one batch merged
    algo.stop()


# ---------------------------------------------------------------- framestack

def test_framestack_rollout_semantics():
    """Stacked obs carry the last N frames: within an episode frame
    t's window ends with obs[t] and starts with obs[t-N+1]; on reset
    the window refills with the fresh obs (reference parity:
    env_to_module frame-stacking connector, fused into the rollout)."""
    N = 4
    T, B = 64, 2     # 64 steps: random-policy CartPole episodes end
    #                  well within this, so reset-refill IS exercised
    r = SingleAgentEnvRunner("CartPole-v1", num_envs=B,
                             rollout_length=T, seed=0, framestack=N)
    out = r.sample()
    b = out["batch"]
    D = 4
    assert b["obs"].shape == (T, B, N * D)
    obs = b["obs"].reshape(T, B, N, D)
    dones = b["dones"]
    # pick steps with no done in the last N-1 steps: window must be a
    # shifted copy of the previous step's
    for t in range(1, T):
        for e in range(B):
            if dones[max(0, t - N):t + 1, e].any():
                continue
            np.testing.assert_allclose(obs[t, e, :-1], obs[t - 1, e, 1:],
                                       rtol=1e-6)
    # after a done at t, the stack at t+1 is N copies of the reset obs
    hits = 0
    for t in range(T - 1):
        for e in range(B):
            if dones[t, e]:
                first = obs[t + 1, e]
                np.testing.assert_allclose(
                    first, np.tile(first[-1], (N, 1)), rtol=1e-6)
                hits += 1
    assert hits > 0, "no episode ended: reset-refill never exercised"
    assert b["final_obs"].shape == (B, N * D)


def test_framestack_ppo_trains():
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_envs_per_env_runner=8,
                         rollout_fragment_length=32, framestack=4)
            .training(minibatch_size=64, num_epochs=1)
            .build())
    m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    algo.stop()


def test_ppo_cnn_learns_pixel_catch():
    """Pixel-scale learning regression (reference role:
    rllib/benchmarks/ppo/benchmark_atari_ppo.py commits Atari reward
    targets; ale-py is not in this image, so the gate is CatchPixels —
    solvable only by reading the image through the CNN module).
    Random play scores about -4 per episode; the committed target is
    +4 (>=75% catch rate)."""
    from ray_tpu.rllib import CNNRLModule
    algo = (PPOConfig().environment("CatchPixels-v0")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=80)
            .training(lr=1e-3, minibatch_size=320, num_epochs=4,
                      entropy_coeff=0.01)
            .rl_module(module_class=CNNRLModule)
            .debugging(seed=0)
            .build())
    first = algo.train()["episode_return_mean"]
    best = first
    for _ in range(40):
        best = max(best, algo.train()["episode_return_mean"])
        if best >= 4.0:
            break
    assert best >= 4.0, f"CNN PPO failed to learn: first={first} best={best}"
    algo.stop()
