"""RLlib-equivalent: envs, GAE/vtrace math, PPO/IMPALA learning.

Modeled on the reference's rllib/tests + tuned_examples learning
regression strategy (SURVEY.md §4.5): small learning runs with reward
thresholds, plus exact-math checks against numpy references.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.rllib import (CartPole, IMPALAConfig, Pendulum, PPOConfig,
                           SingleAgentEnvRunner)
from ray_tpu.rllib.algorithms.impala import vtrace
from ray_tpu.rllib.core.postprocessing import compute_gae


# ---------------------------------------------------------------- envs

def test_cartpole_env_shapes_and_termination():
    env = CartPole(max_episode_steps=10)
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    assert obs.shape == (4,)
    done = False
    for _ in range(10):
        state, obs, reward, done = env.step(
            state, jnp.int32(1), key)
        assert reward == 1.0
    assert bool(done)  # truncated at max_episode_steps


def test_pendulum_env():
    env = Pendulum(max_episode_steps=5)
    state, obs = env.reset(jax.random.PRNGKey(1))
    assert obs.shape == (3,)
    state, obs, reward, done = env.step(
        state, jnp.zeros((1,)), jax.random.PRNGKey(2))
    assert float(reward) <= 0.0 and not bool(done)


def test_env_runner_batch_layout():
    r = SingleAgentEnvRunner("CartPole-v1", num_envs=4, rollout_length=16,
                             seed=0)
    out = r.sample()
    b = out["batch"]
    assert b["obs"].shape == (16, 4, 4)
    assert b["actions"].shape == (16, 4)
    assert b["final_vf"].shape == (4,)
    assert out["stats"]["env_steps"] == 64
    # weights round-trip
    w = r.get_weights()
    r.set_weights(w)


# ---------------------------------------------------------------- math

def _gae_numpy(rewards, values, dones, final_values, gamma, lam):
    T, B = rewards.shape
    adv = np.zeros((T, B))
    next_adv = np.zeros(B)
    next_val = final_values
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_val * nd - values[t]
        next_adv = delta + gamma * lam * nd * next_adv
        adv[t] = next_adv
        next_val = values[t]
    return adv, adv + values


def test_gae_matches_numpy_reference():
    rng = np.random.default_rng(0)
    T, B = 12, 3
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = (rng.random((T, B)) < 0.2).astype(np.float32)
    final = rng.normal(size=B).astype(np.float32)
    adv, targets = compute_gae(rewards, values, dones, final,
                               gamma=0.97, lam=0.9)
    ref_adv, ref_t = _gae_numpy(rewards, values, dones, final, 0.97, 0.9)
    np.testing.assert_allclose(np.asarray(adv), ref_adv, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(targets), ref_t, rtol=1e-4,
                               atol=1e-5)


def test_vtrace_on_policy_reduces_to_gae_lambda1():
    """With target==behavior and no clipping active, vtrace vs equals the
    lambda=1 GAE targets (Espeholt et al. 2018, Remark 1)."""
    rng = np.random.default_rng(1)
    T, B = 10, 2
    logp = rng.normal(size=(T, B)).astype(np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    final = rng.normal(size=B).astype(np.float32)
    vs, _ = vtrace(logp, logp, rewards, values, dones, final, gamma=0.95)
    adv, targets = compute_gae(rewards, values, dones, final,
                               gamma=0.95, lam=1.0)
    np.testing.assert_allclose(np.asarray(vs), np.asarray(targets),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------- learning

def test_ppo_learns_cartpole():
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128)
            .training(lr=3e-4, minibatch_size=256, num_epochs=4)
            .debugging(seed=0)
            .build())
    first = algo.train()["episode_return_mean"]
    best = first
    for _ in range(24):
        best = max(best, algo.train()["episode_return_mean"])
        if best > 120:
            break
    assert best > 120, f"PPO failed to learn: first={first} best={best}"
    # checkpoint round-trip
    ckpt = algo.save()
    algo.restore(ckpt)
    algo.stop()


def test_ppo_continuous_pendulum_runs():
    algo = (PPOConfig().environment("Pendulum-v1")
            .env_runners(num_envs_per_env_runner=8,
                         rollout_fragment_length=64)
            .training(minibatch_size=128, num_epochs=2)
            .build())
    m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    algo.stop()


def test_impala_learns_cartpole():
    algo = (IMPALAConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=64)
            .training(lr=2e-3, entropy_coeff=0.005)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(60):
        best = max(best, algo.train()["episode_return_mean"])
        if best > 80:
            break
    assert best > 80, f"IMPALA failed to learn: best={best}"
    algo.stop()


# ---------------------------------------------------------------- distributed

@pytest.mark.usefixtures("ray_start")
def test_ppo_remote_env_runners(ray_start):
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(minibatch_size=64, num_epochs=2)
            .build())
    m = algo.train()
    assert m["num_env_steps_sampled"] == 2 * 4 * 32
    m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    algo.stop()


@pytest.mark.usefixtures("ray_start")
def test_ppo_multi_learner_allreduce(ray_start):
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(minibatch_size=128, num_epochs=1)
            .learners(num_learners=2)
            .build())
    m1 = algo.train()
    m2 = algo.train()
    assert np.isfinite(m2["learner/total_loss"])
    assert m2["num_env_steps_sampled_lifetime"] == 2 * 8 * 32
    algo.stop()


@pytest.mark.usefixtures("ray_start")
def test_impala_async_remote_runners(ray_start):
    algo = (IMPALAConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .build())
    for _ in range(3):
        m = algo.train()
    assert np.isfinite(m["learner/total_loss"])
    assert m["num_env_steps_sampled_lifetime"] == 3 * 4 * 32
    algo.stop()


# ---------------------------------------------------------------- tune integration

@pytest.mark.usefixtures("ray_start")
def test_ppo_under_tune(ray_start):
    from ray_tpu import tune
    from ray_tpu.rllib import PPO

    results = tune.Tuner(
        PPO,
        param_space={
            "env": "CartPole-v1",
            "num_envs_per_env_runner": 4,
            "rollout_fragment_length": 16,
            "minibatch_size": 32,
            "num_epochs": 1,
            "lr": tune.grid_search([1e-3, 3e-4]),
        },
        tune_config=tune.TuneConfig(stop={"training_iteration": 2}),
    ).fit()
    assert len(results) == 2
    assert all(np.isfinite(r.metrics["learner/total_loss"])
               for r in results)
