"""Real-model path: safetensors loader + native BPE tokenizer.

Covers the role of the reference's vLLM/transformers delegation
(/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:57-63) rebuilt natively: HF-layout checkpoints load
shape/dtype-exact onto a sharded mesh, the trainer and the engine both
consume them, and decode through the loaded engine is token-exact
against the source params (the golden-token gate)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import ml_dtypes

from ray_tpu.models import checkpoint_io, llama
from ray_tpu.parallel import MeshSpec


# --------------------------------------------------------------- safetensors

def test_safetensors_roundtrip_and_slicing(tmp_path):
    path = str(tmp_path / "t.safetensors")
    tensors = {
        "a": np.arange(24, dtype=np.float32).reshape(4, 6),
        "b": np.arange(10, dtype=np.int32),
        "c": (np.ones((3, 2)) * 0.5).astype(ml_dtypes.bfloat16),
    }
    checkpoint_io.write_safetensors(path, tensors, metadata={"format": "pt"})
    f = checkpoint_io.SafeTensorsFile(path)
    assert sorted(f.keys()) == ["a", "b", "c"]
    assert f.metadata == {"format": "pt"}
    for name, t in tensors.items():
        shape, dtype = f.info(name)
        assert shape == t.shape and dtype == t.dtype
        np.testing.assert_array_equal(np.asarray(f.read(name)), t)
    # windowed read touches only the slice
    np.testing.assert_array_equal(
        np.asarray(f.read("a", (slice(1, 3), slice(2, 5)))),
        tensors["a"][1:3, 2:5])


def _write_debug_ckpt(tmp_path, cfg, seed=0, max_shard_bytes=4 << 30):
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    ckpt = str(tmp_path / "ckpt")
    checkpoint_io.save_llama_checkpoint(
        cfg, params, ckpt, max_shard_bytes=max_shard_bytes)
    checkpoint_io.save_config(cfg, ckpt)
    return params, ckpt


def _assert_tree_equal(a, b):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            rtol=0, atol=0)


def test_hf_layout_roundtrip(tmp_path):
    cfg = llama.config("debug")
    params, ckpt = _write_debug_ckpt(tmp_path, cfg)
    loaded = checkpoint_io.load_llama_params(cfg, ckpt)
    _assert_tree_equal(params, loaded)
    # config.json round-trips the architecture
    cfg2 = checkpoint_io.load_config(ckpt)
    assert (cfg2.hidden, cfg2.n_layers, cfg2.n_heads, cfg2.n_kv_heads,
            cfg2.ffn, cfg2.vocab_size) == (
        cfg.hidden, cfg.n_layers, cfg.n_heads, cfg.n_kv_heads,
        cfg.ffn, cfg.vocab_size)


def test_hf_layout_roundtrip_sharded_files(tmp_path):
    """Tiny max_shard_bytes forces the multi-file + index.json path."""
    cfg = llama.config("debug")
    params, ckpt = _write_debug_ckpt(tmp_path, cfg,
                                     max_shard_bytes=64 * 1024)
    assert os.path.exists(
        os.path.join(ckpt, "model.safetensors.index.json"))
    loaded = checkpoint_io.load_llama_params(cfg, ckpt)
    _assert_tree_equal(params, loaded)


def test_hf_layout_roundtrip_moe(tmp_path):
    cfg = llama.config("debug_moe")
    params, ckpt = _write_debug_ckpt(tmp_path, cfg)
    loaded = checkpoint_io.load_llama_params(cfg, ckpt)
    _assert_tree_equal(params, loaded)


def test_tied_embeddings_fallback(tmp_path):
    """No lm_head tensor (Llama-3.2-style tying) -> embed.T is used."""
    cfg = llama.config("debug")
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    checkpoint_io.save_llama_checkpoint(cfg, params, ckpt)
    # rewrite the single shard without lm_head.weight
    f = checkpoint_io.SafeTensorsFile(
        os.path.join(ckpt, "model.safetensors"))
    # materialize copies: read() returns mmap VIEWS into the very file
    # the next line overwrites (SIGBUS otherwise)
    tensors = {k: np.array(f.read(k)) for k in f.keys()
               if k != "lm_head.weight"}
    checkpoint_io.write_safetensors(
        os.path.join(ckpt, "model.safetensors"), tensors)
    loaded = checkpoint_io.load_llama_params(cfg, ckpt)
    np.testing.assert_array_equal(
        np.asarray(loaded["lm_head"], np.float32),
        np.asarray(params["embed"], np.float32).T)


def test_sharded_load_on_mesh(tmp_path):
    """fsdp x tp mesh: values identical to the unsharded load and every
    leaf lands under its logical-axis NamedSharding."""
    cfg = llama.config("debug")
    params, ckpt = _write_debug_ckpt(tmp_path, cfg)
    mesh = MeshSpec(dp=1, fsdp=2, sp=1, tp=4).build(jax.devices()[:8])
    loaded = checkpoint_io.load_llama_params(cfg, ckpt, mesh=mesh)
    _assert_tree_equal(params, loaded)
    from ray_tpu.parallel.sharding import tree_shardings
    expect = tree_shardings(llama.param_logical_axes(cfg), mesh)
    got_ok = jax.tree.map(
        lambda arr, sh: arr.sharding.is_equivalent_to(sh, arr.ndim),
        loaded, expect)
    assert all(jax.tree.leaves(got_ok)), got_ok


def test_llama38b_layout_shape_exact(tmp_path):
    """The Llama-3-8B architecture (depth truncated to keep the file
    small — every tensor ROLE and orientation is exercised) loads
    shape/dtype-exact on the virtual fsdp x tp mesh: the VERDICT r4
    north-star gate for the real-model path."""
    cfg = llama.config("8b", n_layers=2, max_seq=256)
    rng = np.random.default_rng(0)
    # synthetic bf16 weights in true HF layout/orientation; content
    # is only ever asserted on the layer-0 q_proj orientation probe
    # below, so everything else is zeros — generating ~1.5G random
    # f64s dominated this test's runtime for bytes nobody reads
    tensors = {}

    def t(shape, random=False):
        if random:
            return rng.standard_normal(shape).astype(
                ml_dtypes.bfloat16)
        return np.zeros(shape, ml_dtypes.bfloat16)

    tensors["model.embed_tokens.weight"] = t((cfg.vocab_size, cfg.hidden))
    tensors["model.norm.weight"] = t((cfg.hidden,))
    tensors["lm_head.weight"] = t((cfg.vocab_size, cfg.hidden))
    for l in range(cfg.n_layers):
        p = f"model.layers.{l}."
        tensors[p + "self_attn.q_proj.weight"] = t(
            (cfg.q_dim, cfg.hidden), random=(l == 0))
        tensors[p + "self_attn.k_proj.weight"] = t((cfg.kv_dim, cfg.hidden))
        tensors[p + "self_attn.v_proj.weight"] = t((cfg.kv_dim, cfg.hidden))
        tensors[p + "self_attn.o_proj.weight"] = t((cfg.hidden, cfg.q_dim))
        tensors[p + "mlp.gate_proj.weight"] = t((cfg.ffn, cfg.hidden))
        tensors[p + "mlp.up_proj.weight"] = t((cfg.ffn, cfg.hidden))
        tensors[p + "mlp.down_proj.weight"] = t((cfg.hidden, cfg.ffn))
        tensors[p + "input_layernorm.weight"] = t((cfg.hidden,))
        tensors[p + "post_attention_layernorm.weight"] = t((cfg.hidden,))
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    checkpoint_io.write_safetensors(
        os.path.join(ckpt, "model.safetensors"), tensors)
    checkpoint_io.save_config(cfg, ckpt)

    mesh = MeshSpec(dp=1, fsdp=2, sp=1, tp=4).build(jax.devices()[:8])
    loaded = checkpoint_io.load_llama_params(
        cfg, ckpt, mesh=mesh, dtype=jnp.bfloat16)
    axes = llama.param_logical_axes(cfg)
    shapes = jax.tree.map(lambda a: a.shape, loaded)
    assert shapes["layers"]["wq"] == (cfg.n_layers, cfg.hidden, cfg.q_dim)
    assert shapes["lm_head"] == (cfg.hidden, cfg.vocab_size)
    assert all(a.dtype == jnp.bfloat16 for a in jax.tree.leaves(loaded))
    # orientation check: wq row 0 of layer 0 == HF q_proj column 0
    np.testing.assert_array_equal(
        np.asarray(loaded["layers"]["wq"][0, 0], ml_dtypes.bfloat16),
        tensors["model.layers.0.self_attn.q_proj.weight"][:, 0])
    del axes


# ------------------------------------------------------------ consumers

def test_trainer_consumes_checkpoint(tmp_path):
    from ray_tpu.models.training import TrainStepBundle
    cfg = llama.config("debug")
    params, ckpt = _write_debug_ckpt(tmp_path, cfg)
    mesh = MeshSpec(dp=2, fsdp=2, sp=1, tp=2).build(jax.devices()[:8])
    bundle = TrainStepBundle(cfg, mesh)
    state = bundle.init_state_from_checkpoint(ckpt)
    tokens = bundle.shard_batch(jnp.zeros((4, 64), jnp.int32))
    state, metrics = bundle.step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))


def test_engine_golden_token_decode(tmp_path):
    """Engine built from the CHECKPOINT decodes token-exact against the
    engine built from the source params."""
    from ray_tpu.llm import (EngineConfig, InferenceEngine, Request,
                             SamplingParams)
    cfg = llama.config("debug", dtype=jnp.float32)
    params, ckpt = _write_debug_ckpt(tmp_path, cfg)

    def run(engine):
        req = Request("g", list(range(5, 29)),
                      SamplingParams(max_tokens=12, temperature=0.0))
        engine.add_request(req)
        while not req.finished:
            engine.step()
        return list(req.output_tokens)

    base = run(InferenceEngine(EngineConfig(model=cfg), params=params))
    # same compute dtype both sides (config.json does not carry dtype;
    # architecture-from-config is asserted in test_hf_layout_roundtrip)
    from_ckpt = run(InferenceEngine(
        EngineConfig(model=cfg, checkpoint=ckpt)))
    assert base == from_ckpt and len(base) == 12
    # model=None resolves the architecture from the checkpoint config
    eng = InferenceEngine(EngineConfig(model=None, checkpoint=ckpt))
    assert eng.model_cfg.hidden == cfg.hidden


# ------------------------------------------------------------------- BPE

SAMPLES = [
    "Hello, world!",
    "The quick brown fox jumps over 1234 lazy dogs.",
    "  leading spaces and\nnewlines\t tabs",
    "unicode: café — über 寿司 \U0001f680",
    "don't stop, it's fine; we'll see...",
    "CamelCase snake_case kebab-case 42x",
]


def _train_tiny_tokenizer(tmp_path):
    """Train a real byte-level BPE with the tokenizers library (the
    Rust reference implementation) to act as an exactness oracle."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders
    from tokenizers.trainers import BpeTrainer
    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False,
                                                 use_regex=True)
    tok.decoder = decoders.ByteLevel()
    trainer = BpeTrainer(
        vocab_size=500, special_tokens=["<|bos|>", "<|eos|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    corpus = SAMPLES * 20 + ["the and of to in is was for on hello world"]
    tok.train_from_iterator(corpus, trainer)
    path = str(tmp_path / "tokenizer.json")
    tok.save(path)
    return tok, path


def test_bpe_matches_rust_reference(tmp_path):
    rust, path = _train_tiny_tokenizer(tmp_path)
    from ray_tpu.llm._internal import bpe
    ours = bpe.load(path)
    for s in SAMPLES:
        expect = rust.encode(s).ids
        got = ours.encode(s, add_bos=False)
        assert got == expect, (s, got, expect)
        assert ours.decode(got) == rust.decode(expect)


def test_bpe_special_tokens_and_chat(tmp_path):
    _, path = _train_tiny_tokenizer(tmp_path)
    from ray_tpu.llm._internal import bpe
    tok = bpe.load(path)
    bos = tok.special["<|bos|>"]
    eos = tok.special["<|eos|>"]
    ids = tok.encode("<|bos|>hi<|eos|>", add_bos=False)
    assert ids[0] == bos and ids[-1] == eos
    assert tok.decode(ids) == "hi"
    assert tok.decode(ids, skip_special_tokens=False) == (
        "<|bos|>hi<|eos|>")
    out = tok.apply_chat_template(
        [{"role": "user", "content": "hello"}])
    assert "user" in out and out.endswith("\n")


def test_load_tokenizer_prefers_native_bpe(tmp_path):
    _, path = _train_tiny_tokenizer(tmp_path)
    from ray_tpu.llm._internal.tokenizer import load_tokenizer
    from ray_tpu.llm._internal.bpe import BPETokenizer
    tok = load_tokenizer(str(tmp_path))
    assert isinstance(tok, BPETokenizer)


def test_sentencepiece_style_spec_rejected(tmp_path):
    """Llama-2/Mistral-style tokenizer.json (byte_fallback, \\u2581
    vocab, no ByteLevel) must NOT route to the native byte-level
    encoder — it would silently tokenize wrong."""
    from ray_tpu.llm._internal import bpe
    spec = {
        "model": {"type": "BPE", "byte_fallback": True,
                  "vocab": {"▁the": 5, "a": 6}, "merges": []},
        "pre_tokenizer": None,
        "added_tokens": [],
    }
    p = tmp_path / "tokenizer.json"
    p.write_text(json.dumps(spec))
    assert not bpe.is_byte_level_spec(str(p))
    # byte-level spec accepted
    sub = tmp_path / "bl"
    sub.mkdir()
    _, path = _train_tiny_tokenizer(sub)
    assert bpe.is_byte_level_spec(path)


def test_bpe_no_double_bos_on_chat_template(tmp_path):
    """apply_chat_template embeds the BOS literal; encode must not
    prepend a second one."""
    _, path = _train_tiny_tokenizer(tmp_path)
    from ray_tpu.llm._internal import bpe
    tok = bpe.load(path)
    # force llama-3-style naming onto the trained specials
    tok.bos_token = "<|bos|>"
    tok.bos_id = tok.special["<|bos|>"]
    ids = tok.encode("<|bos|>hello", add_bos=True)
    assert ids.count(tok.bos_id) == 1
    # plain text still gets exactly one
    ids = tok.encode("hello", add_bos=True)
    assert ids.count(tok.bos_id) == 1 and ids[0] == tok.bos_id
