"""Importable serve app for YAML-deploy tests (the import_path target)."""

from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x):
        return x * 2


@serve.deployment
class Gateway:
    def __init__(self, doubler):
        self.doubler = doubler

    async def __call__(self, x):
        return await self.doubler.remote(x) + 1


app = Gateway.bind(Doubler.bind())


def build_app(args=None):
    """Builder form: `import_path: serve_test_app:build_app` + args."""
    bias = (args or {}).get("bias", 0)

    @serve.deployment(name="Biaser")
    def biaser(x):
        return x + bias

    return biaser.bind()
