"""Fleet traffic simulator gates (ISSUE 14).

The acceptance contract: the simulator drives the PRODUCTION policy
classes (asserted by identity), the same seed + trace produce a
byte-identical run summary, >=1M simulated sessions replay with fleet
SLO assertions and an emitted capacity-curve artifact, autoscaler
hysteresis stays bounded over >=24h of simulated diurnal time, and
the committed CPU calibration pins sim predictions against a real
engine within the tolerance band.
"""

import dataclasses
import json
import os

import pytest

jax = pytest.importorskip("jax")

from ray_tpu.serve import llm as serve_llm  # noqa: E402
from ray_tpu.serve.llm import (AdmissionConfig,  # noqa: E402
                               AdmissionController, AutoscaleConfig,
                               FleetAutoscaler, FleetRouter,
                               CircuitBreaker, SLOBurnWatchdog)
from ray_tpu.serve.llm.sim import (CALIBRATION_BAND,  # noqa: E402
                                   FleetSimulator, SimCalibration,
                                   SimFleetConfig, TraceConfig,
                                   VirtualClock, assert_slos,
                                   batch_backlog, capacity_curve,
                                   chaos_overlay,
                                   default_cpu_calibration, generate,
                                   write_artifact)

CALIB = default_cpu_calibration()


def _cfg(**kw):
    base = dict(replicas=4, min_replicas=2, slots_per_replica=8,
                pages_per_replica=2048, calibration=CALIB, seed=3,
                admission=AdmissionConfig(max_concurrent=96,
                                          max_queue=256,
                                          queue_wait_slo_s=5.0))
    base.update(kw)
    return SimFleetConfig(**base)


def _trace(**kw):
    base = dict(kind="diurnal", sessions=20_000, duration_s=7200.0,
                seed=3, prefix_groups=64, prompt_tokens_mean=24,
                prompt_tokens_max=96, out_tokens_mean=12,
                out_tokens_max=48)
    base.update(kw)
    return TraceConfig(**base)


# ------------------------------------------------ the policy identity
def test_simulator_drives_production_policy_classes():
    """THE anti-fork gate: the objects inside the simulator ARE the
    production classes, imported from their production modules — a
    policy bug the sim finds is a bug the fleet ships."""
    sim = FleetSimulator(generate(_trace(sessions=10)), _cfg())
    assert type(sim.router) is FleetRouter
    assert type(sim.admission) is AdmissionController
    assert type(sim.autoscaler) is FleetAutoscaler
    assert type(sim.watchdog) is SLOBurnWatchdog
    assert all(type(b) is CircuitBreaker for b in sim.breakers)
    # and they are the very classes serve.llm exports
    assert sim.router.__class__ is serve_llm.FleetRouter
    assert sim.admission.__class__ is serve_llm.AdmissionController
    assert sim.autoscaler.__class__ is serve_llm.FleetAutoscaler
    assert sim.watchdog.__class__ is serve_llm.SLOBurnWatchdog
    # virtual-clocked, not wall-clocked (the ISSUE 14 satellite):
    # every policy's injected clock is a bound method of THE sim
    # clock (bound-method objects differ per access; the receiver
    # identity is the contract)
    for obj in (sim.router, sim.admission, sim.autoscaler,
                sim.watchdog, *sim.breakers):
        assert getattr(obj._clock, "__self__", None) is sim.clock


def test_virtual_clock_only_time_source():
    """A run must never consult the wall clock: freezing real time
    has no effect, and the summary's virtual span tracks the trace's
    duration, not host time."""
    tc = _trace(sessions=2000, duration_s=3600.0)
    sim = FleetSimulator(generate(tc), _cfg())
    s = sim.run()
    # virtual span tracks the trace (last arrival + drain), far past
    # anything host time could reach in this test
    assert s["sim"]["virtual_s"] >= 0.9 * 3600.0
    assert s["sessions"]["completed"] > 0


# ---------------------------------------------------- determinism gate
def test_same_seed_byte_identical_summary():
    tc = _trace(sessions=8_000)
    jobs = batch_backlog(200, out_tokens=16)
    a = FleetSimulator(generate(tc), _cfg(), batch_jobs=jobs)
    a.run()
    b = FleetSimulator(generate(tc), _cfg(),
                       batch_jobs=batch_backlog(200, out_tokens=16))
    b.run()
    assert a.summary_json() == b.summary_json()


def test_different_seed_diverges():
    a = FleetSimulator(generate(_trace(sessions=5000, seed=3)),
                       _cfg(seed=3))
    b = FleetSimulator(generate(_trace(sessions=5000, seed=4)),
                       _cfg(seed=4))
    a.run()
    b.run()
    assert a.summary_json() != b.summary_json()


def test_trace_generator_deterministic_and_sorted():
    tc = _trace(sessions=5000)
    a = list(generate(tc))
    b = list(generate(tc))
    assert [(s.at, s.tenant, s.group, s.prompt_tokens, s.out_tokens)
            for s in a] == \
           [(s.at, s.tenant, s.group, s.prompt_tokens, s.out_tokens)
            for s in b]
    assert all(x.at <= y.at for x, y in zip(a, b[1:]))
    assert a[-1].at <= tc.duration_s


# ------------------------------------------------------- traffic shapes
def test_flash_crowd_concentrates_arrivals():
    tc = _trace(kind="flash_crowd", sessions=20_000, crowds=2,
                crowd_fraction=0.5, crowd_width_s=120.0)
    arrivals = [s.at for s in generate(tc)]
    # half the mass lands inside ~2*120s of a 7200s trace
    windows = sorted(arrivals)
    from collections import Counter
    by_bin = Counter(int(a // 120) for a in arrivals)
    top2 = sum(c for _, c in by_bin.most_common(4))
    assert top2 >= 0.4 * len(arrivals)


def test_tenant_skew_zipf_weighted():
    tc = _trace(kind="tenant_skew", sessions=20_000, tenants=6)
    from collections import Counter
    c = Counter(s.tenant for s in generate(tc))
    assert c["t0"] > 2 * c["t5"]


# -------------------------------------------------- chaos + breakers
def test_chaos_death_drives_breaker_eviction_and_recovery():
    # death at the diurnal PEAK (duration/2) of a hot trace, so the
    # victim is guaranteed residents to fail over
    tc = _trace(sessions=40_000, duration_s=3600.0,
                out_tokens_mean=32)
    chaos = [serve_llm.sim.ChaosEvent(at=1800.0, replica=1,
                                      kind="die", duration_s=600.0)]
    sim = FleetSimulator(generate(tc), _cfg(replicas=3,
                                            min_replicas=3),
                         chaos=chaos)
    s = sim.run()
    assert s["health"]["evictions"] >= 1
    assert s["health"]["readmissions"] >= 1
    assert s["sessions"]["failed_over"] >= 1
    assert_slos(s, min_completion_rate=0.99)


def test_chaos_overlay_seeded():
    tc = _trace(sessions=100)
    a = chaos_overlay(tc, replicas=4, events=3)
    b = chaos_overlay(tc, replicas=4, events=3)
    assert [(e.at, e.replica, e.kind) for e in a] == \
           [(e.at, e.replica, e.kind) for e in b]


# ------------------------------------- autoscaler hysteresis property
def test_autoscaler_hysteresis_bounded_over_24h_diurnal():
    """Satellite gate: >=24h of simulated diurnal traffic, replica
    count stays within [min,max] and the transition count is bounded
    (no flapping) — at most a few scale events per diurnal swing."""
    tc = _trace(sessions=80_000, duration_s=86_400.0,
                diurnal_amplitude=0.9)
    cfg = _cfg(replicas=8, min_replicas=2,
               autoscale=AutoscaleConfig(
                   min_replicas=2, max_replicas=8,
                   upscale_delay_s=30.0, downscale_delay_s=300.0),
               control_period_s=5.0, autoscale_period_s=15.0)
    sim = FleetSimulator(generate(tc), cfg)
    s = sim.run()
    assert 2 <= s["autoscale"]["active_min"] \
        <= s["autoscale"]["active_max"] <= 8
    # bounded transitions: one diurnal cycle should cost at most a
    # handful of scale events each way, never a flap storm
    assert s["autoscale"]["events"] <= 24, s["autoscale"]
    assert_slos(s, min_completion_rate=0.99)


# --------------------------------------------------- the million gate
def test_million_sessions_with_slos_and_capacity_artifact(tmp_path):
    """THE scale gate: >=1M simulated sessions replay on CPU with
    fleet SLO assertions, and the capacity sweep emits its artifact
    (replicas vs p99 TTFT)."""
    tc = _trace(sessions=1_000_000, duration_s=86_400.0, seed=14,
                tenants=8, prefix_groups=512)
    cfg = _cfg(replicas=12, min_replicas=6, slots_per_replica=16,
               pages_per_replica=4096, seed=14,
               control_period_s=10.0, autoscale_period_s=30.0,
               admission=AdmissionConfig(max_concurrent=384,
                                         max_queue=1024,
                                         queue_wait_slo_s=5.0))
    sim = FleetSimulator(generate(tc), cfg,
                         batch_jobs=batch_backlog(2000,
                                                  out_tokens=16))
    s = sim.run()
    assert s["sessions"]["arrived"] >= 1_000_000
    assert_slos(s, max_shed_rate=0.05, min_completion_rate=0.99)
    assert s["batch"]["completed"] == 2000
    assert s["batch"]["tokens"] > 0

    # capacity curve over a downsampled replay of the same shape
    curve = capacity_curve(
        dataclasses.replace(tc, sessions=30_000,
                            duration_s=3600.0),
        _cfg(slots_per_replica=16, pages_per_replica=4096),
        replica_counts=[2, 4, 8])
    path = write_artifact(curve,
                          os.path.join(tmp_path, "capacity.json"))
    doc = json.loads(open(path).read())
    assert doc["object"] == "capacity_curve"
    assert [p["replicas"] for p in doc["points"]] == [2, 4, 8]
    # more replicas never makes the tail WORSE on the same traffic
    p99 = [p["p99_ttft_ms"] for p in doc["points"]]
    assert p99[-1] <= p99[0]


# --------------------------------------------- slice topology (ISSUE 17)
def test_sim_chips_scale_tick_rate():
    """A 2-chip slice replica decodes ~2x faster (the calibration's
    single-chip tick duration divides by the slice size): same trace
    and seed, chips_per_replica=2 must tighten the interactive ITL
    materially while completing at least as many sessions."""
    tc = _trace(sessions=4000, duration_s=3600.0)
    one = FleetSimulator(generate(tc), _cfg()).run()
    two = FleetSimulator(generate(tc),
                         _cfg(chips_per_replica=2)).run()
    assert two["sim"]["chips_per_replica"] == 2
    assert (two["sessions"]["completed"]
            >= one["sessions"]["completed"])
    itl1 = one["latency"]["itl"]["mean_ms"]
    itl2 = two["latency"]["itl"]["mean_ms"]
    assert itl2 < 0.75 * itl1, (itl1, itl2)


def test_capacity_curve_prices_per_chip():
    """The sweep prices every operating point per chip: a 2-chip
    slice that doesn't buy the tail is capacity the per-replica view
    would hide."""
    curve = capacity_curve(
        _trace(sessions=2000, duration_s=1800.0),
        _cfg(chips_per_replica=2), replica_counts=[2, 4])
    assert curve["fleet"]["chips_per_replica"] == 2
    pts = curve["points"]
    assert [p["chips"] for p in pts] == [4, 8]
    for p in pts:
        assert p["tokens_per_chip_s"] > 0
        assert p["chip_s_per_1k_tokens"] > 0
    # same traffic over 2x the chips: per-chip throughput drops, so
    # the chip-seconds cost of 1k tokens rises — the cost metric
    # really is per chip, not per replica
    assert pts[1]["tokens_per_chip_s"] < pts[0]["tokens_per_chip_s"]


# --------------------------------------------- batch soak inside sim
def test_sim_batch_lane_soaks_trough_without_regression():
    """The simulator models the lane the fleet ships: batch backlog
    soaks the diurnal trough, interactive tails unchanged vs a
    lane-off A/B on the same seed."""
    tc = _trace(sessions=15_000, duration_s=14_400.0)

    def run(jobs):
        sim = FleetSimulator(generate(tc), _cfg(), batch_jobs=jobs)
        return sim.run()

    off = run([])
    on = run(batch_backlog(400, out_tokens=24))
    assert on["batch"]["completed"] == 400
    assert on["batch"]["tokens"] >= 400 * 24 * 0.9
    # interactive TAIL unchanged: one 1.15x log-histogram bin of p99
    # slack (bin quantization only). The mean may shift by a couple
    # of tick-times — co-residency with soaked batch work runs
    # interactive sessions in a larger batch — so it is bounded
    # absolutely (4 full-batch ticks), never relatively
    p99_off = off["latency"]["ttft"]["p99_ms"]
    p99_on = on["latency"]["ttft"]["p99_ms"]
    assert p99_on <= p99_off * 1.16 + 1.0, (p99_off, p99_on)
    mean_off = off["latency"]["ttft"]["mean_ms"]
    mean_on = on["latency"]["ttft"]["mean_ms"]
    assert mean_on <= mean_off + 4 * CALIB.tick_point(8, "p50"), (
        mean_off, mean_on)
    # the engine-level gate pins the token-exact preemption path
    # (test_batch_lane); here the lane must only soak, not regress


# ----------------------------------------------- calibration fidelity
def test_calibration_roundtrip_and_fallbacks():
    c = SimCalibration(
        name="t", decode_tick_ms={"2": {"p50": 1.0, "p95": 2.0,
                                        "p99": 3.0}},
        prefill_ms_per_token=0.1, prefill_chunk_tokens=64)
    c2 = SimCalibration.from_json(c.to_json())
    assert dataclasses.asdict(c2) == dataclasses.asdict(c)
    # bucket fallbacks: below -> nearest, above -> linear scale
    assert c.tick_point(1, "p50") == 1.0
    assert c.tick_point(8, "p50") == 4.0
    assert c.prefill_ticks(129) == 3
    assert c.draw_tick_ms(2, 0, 0.0) == 1.0
    assert c.draw_tick_ms(2, 0, 0.999) == 3.0
    assert c.draw_tick_ms(2, 10, 0.0) == 2.0


def test_committed_cpu_calibration_loads():
    assert CALIB.decode_tick_ms, "calibration_cpu.json is empty"
    assert CALIB.page_size > 0
    p50 = CALIB.tick_point(1, "p50")
    assert 0.01 <= p50 <= 1000.0


@pytest.mark.slow
def test_sim_vs_real_calibration_band():
    """The A/B that keeps the committed file honest: drive a real
    debug engine through a small workload, replay the same workload
    through the simulator under the committed calibration, and pin
    the predicted mean e2e within CALIBRATION_BAND of measured.
    Slow-marked: the real half builds and runs an engine (~tens of
    seconds); bench_llm --smoke carries the tier-1 twin."""
    import time as _t
    from tools.simcal import build_engine, check_against
    from ray_tpu.llm._internal.engine import Request, SamplingParams

    n, prompt_len, out = 12, 24, 16
    eng = build_engine(offload=False)
    # warm the compile caches so measurement is steady-state
    warm = Request("warm", list(range(2, 2 + prompt_len)),
                   SamplingParams(max_tokens=4))
    eng.add_request(warm)
    while not warm.finished:
        eng.step()
    reqs = [Request(f"w{i}", list(range(2 + i, 2 + i + prompt_len)),
                    SamplingParams(max_tokens=out))
            for i in range(n)]
    t0 = _t.monotonic()
    for r in reqs:
        eng.add_request(r)
    while not all(r.finished for r in reqs):
        eng.step()
    real_e2e = (_t.monotonic() - t0)  # batch wall ~ mean e2e (all
    #                                   arrive at once, finish near
    #                                   together)

    sessions = [serve_llm.sim.SimSession(0.0, "t", i, prompt_len,
                                         out, sid=i)
                for i in range(n)]
    sim = FleetSimulator(iter(sessions),
                         _cfg(replicas=1, min_replicas=1,
                              slots_per_replica=8,
                              control_period_s=0.05))
    s = sim.run()
    verdict = check_against(CALIB, s, real_e2e)
    assert verdict["within_band"], verdict


# ---------------------------------------------- sync admission surface
def test_admission_sync_twin_matches_policy():
    """The clock-driven admission surface the simulator relies on:
    submit/grant/shed with an injected virtual clock, same counters
    as the async path."""
    clock = VirtualClock()
    adm = AdmissionController(
        AdmissionConfig(max_concurrent=2, max_queue=2,
                        queue_wait_slo_s=1.0),
        clock=clock.now)
    t1 = adm.submit("a")
    t2 = adm.submit("a")
    assert [t.granted for t in (t1, t2)] == [True, True]
    assert len(adm.granted_sync()) == 2
    t3 = adm.submit("a")
    t4 = adm.submit("b")
    assert not t3.granted and not t4.granted
    with pytest.raises(serve_llm.AdmissionRejected) as ei:
        adm.submit("a")
    assert ei.value.reason == "queue_full"
    # SLO timer in virtual time
    clock.t = 2.0
    shed = adm.shed_expired()
    assert {t.tenant for t in shed} == {"a", "b"}
    assert adm.rejected["queue_wait_slo"] == 2
    assert adm.shed_total == 2
    # release grants nothing (queue empty), counters consistent
    adm.release()
    assert adm.granted_sync() == []
    assert adm.stats()["queued"] == 0


def test_admission_sync_weighted_fair_order():
    clock = VirtualClock()
    adm = AdmissionController(
        AdmissionConfig(max_concurrent=1, max_queue=16,
                        tenant_weights={"heavy": 4.0}),
        clock=clock.now)
    first = adm.submit("x")          # takes the slot
    assert first.granted
    adm.granted_sync()
    order = []
    for i in range(3):
        adm.submit("light")
        adm.submit("heavy")
        adm.submit("heavy")
    for _ in range(9):
        adm.release()
        order += [t.tenant for t in adm.granted_sync()]
    # stride scheduling: heavy (weight 4) drains ~2 per light
    assert order.count("heavy") == 6 and order.count("light") == 3
    assert order[:3].count("heavy") >= 2
