"""Perf-regression gate (ISSUE 11, tools/perfdiff + PERF_BASELINE.json).

The committed baseline is an ASSERTED artifact: the canonical workload
re-runs here and its exact fields (closed-form model costs + the
deterministic dispatch mix and token totals) must match the baseline
to rounding — a drifted cost formula, an extra dispatch per tick, or a
changed packing plan fails tier-1, not just a bench run someone has to
read. compare() semantics are unit-tested on synthetic fingerprints.
"""

import copy
import json

import pytest

from tools import perfdiff


@pytest.fixture(scope="module")
def canonical_fp():
    """One canonical-workload run shared by the gate tests (the
    workload is deterministic, so sharing loses nothing)."""
    return perfdiff.run_canonical_workload()


def _fp(**over):
    fp = {
        "schema": perfdiff.SCHEMA,
        "exact": {"ticks": 10, "dispatches": 10,
                  "flops_total": 1000.0},
        "noisy": {"tokens_per_s": 100.0, "mfu": 0.5},
    }
    fp.update(over)
    return fp


# ------------------------------------------------------ compare() unit

def test_compare_identical_passes():
    assert perfdiff.compare(_fp(), _fp()) == []


def test_compare_exact_drift_fails():
    cur = copy.deepcopy(_fp())
    cur["exact"]["dispatches"] = 11
    failures = perfdiff.compare(_fp(), cur)
    assert len(failures) == 1
    assert "dispatches" in failures[0] and "drifted" in failures[0]


def test_compare_exact_float_tolerates_rounding_only():
    cur = copy.deepcopy(_fp())
    cur["exact"]["flops_total"] = 1000.0 * (1 + 1e-9)   # rounding
    assert perfdiff.compare(_fp(), cur) == []
    cur["exact"]["flops_total"] = 1000.5                # real drift
    assert perfdiff.compare(_fp(), cur)


def test_compare_missing_metric_fails():
    cur = copy.deepcopy(_fp())
    del cur["exact"]["ticks"]
    del cur["noisy"]["mfu"]
    failures = perfdiff.compare(_fp(), cur)
    assert any("ticks" in f and "missing" in f for f in failures)
    assert any("mfu" in f and "missing" in f for f in failures)


def test_compare_noisy_band_semantics():
    base = _fp()
    cur = copy.deepcopy(base)
    cur["noisy"]["tokens_per_s"] = 60.0      # 0.6x: inside wide band
    assert perfdiff.compare(base, cur) == []
    cur["noisy"]["tokens_per_s"] = 0.5       # 0.005x: catastrophe
    failures = perfdiff.compare(base, cur)
    assert failures and "tokens_per_s" in failures[0]
    # per-metric band override in the baseline wins
    tight = copy.deepcopy(base)
    tight["bands"] = {"tokens_per_s": (0.9, 1.1)}
    cur["noisy"]["tokens_per_s"] = 60.0
    assert perfdiff.compare(tight, cur)


def test_compare_schema_mismatch_short_circuits():
    cur = _fp(schema=99)
    failures = perfdiff.compare(_fp(), cur)
    assert failures == [f"schema mismatch: baseline {perfdiff.SCHEMA} "
                        f"vs current 99"]


# --------------------------------------------- the committed baseline

def test_committed_baseline_parses_and_has_the_gate_fields():
    base = perfdiff.load_baseline()
    assert base["schema"] == perfdiff.SCHEMA
    for key in ("dispatches_per_step", "flops_total", "decode_tokens",
                "gemm_flops_per_token", "kv_bytes_per_token"):
        assert key in base["exact"], key
    # the headline discipline is pinned at exactly one dispatch/tick
    assert base["exact"]["dispatches_per_step"] == 1.0
    assert base["exact"]["flops_total"] > 0


def test_canonical_workload_matches_committed_baseline(canonical_fp):
    """THE regression gate: re-run the canonical workload and diff it
    against PERF_BASELINE.json. Every exact field is deterministic on
    any machine (token COUNTS are pinned by max_tokens even where
    near-tie argmax values flip), so a mismatch is a real change —
    update the baseline deliberately via
    `python -m tools.perfdiff --write-baseline` and justify it in the
    commit, exactly like the jaxlint baseline."""
    baseline = perfdiff.load_baseline()
    failures = perfdiff.compare(baseline, canonical_fp)
    assert not failures, "\n".join(failures)


def test_fingerprint_round_trips_through_json(canonical_fp):
    again = json.loads(json.dumps(canonical_fp))
    assert perfdiff.compare(canonical_fp, again) == []
    assert perfdiff.compare(again, canonical_fp) == []
