"""Controller persistence + restart (reference parity:
gcs_table_storage.h:213 / redis_store_client.h — GCS survives restart).

A controller is killed and a fresh one started from the same SQLite
state: named actors resolve, KV survives, live actors stay reachable
after their daemon re-registers via the heartbeat 'unknown' path."""

import asyncio
import os
import time
import uuid

import pytest

import ray_tpu
from ray_tpu._private.controller import Controller
from ray_tpu._private.daemon import NodeDaemon
from ray_tpu._private.gcs_store import GcsStore


def test_gcs_store_roundtrip(tmp_path):
    store = GcsStore(str(tmp_path / "gcs.db"))
    store.put("kv", "a", b"1")
    store.put("actors", "x", {"state": "ALIVE", "addr": ("h", 1)})
    store.delete("kv", "missing")
    assert store.get("kv", "a") == b"1"
    assert store.get("actors", "x")["state"] == "ALIVE"
    store.close()
    # reopen: state survives process boundary
    store2 = GcsStore(str(tmp_path / "gcs.db"))
    assert dict(store2.items("kv")) == {"a": b"1"}
    store2.close()


def _run(coro):
    return asyncio.get_event_loop().run_until_complete(coro)


def test_controller_restart_restores_tables(tmp_path):
    path = str(tmp_path / "gcs.db")

    async def phase1():
        c = Controller("sess-restart", persist_path=path)
        await c.start()
        await c.rpc_kv_put("cfg/key", b"value1")
        # simulate a named actor lifecycle: submitted + started
        spec = {"task_id": "t1", "actor_id": "a1", "actor_name": "svc",
                "namespace": "default", "is_actor_creation": True,
                "name": "Svc.__init__", "resources": {},
                "return_id": "r1", "owner_addr": ("127.0.0.1", 1),
                "max_restarts": 0}
        c._register_pending_actor(spec, "node-1")
        await c.rpc_actor_started("a1", ("127.0.0.1", 4242), "w1")
        await c.rpc_create_placement_group(
            "pg1", [{"CPU": 1.0}], "PACK", "mypg")
        await c.stop()

    async def phase2():
        c = Controller("sess-restart", persist_path=path)
        await c.start()
        try:
            assert await c.rpc_kv_get("cfg/key") == b"value1"
            info = await c.rpc_get_named_actor("svc")
            assert info is not None and info["actor_id"] == "a1"
            assert tuple(info["addr"]) == ("127.0.0.1", 4242)
            assert info["state"] == "ALIVE"
            assert "pg1" in c.placement_groups
            # unknown node heartbeats are told to re-register
            reply = await c.rpc_heartbeat("node-1")
            assert reply["status"] == "unknown"
        finally:
            await c.stop()

    asyncio.run(phase1())
    asyncio.run(phase2())


def test_dead_actor_stays_dead_after_restart(tmp_path):
    path = str(tmp_path / "gcs.db")

    async def phase1():
        c = Controller("sess-dead", persist_path=path)
        await c.start()
        spec = {"task_id": "t1", "actor_id": "a1", "actor_name": "gone",
                "namespace": "default", "is_actor_creation": True,
                "name": "G.__init__", "resources": {},
                "return_id": "r1", "owner_addr": ("127.0.0.1", 1),
                "max_restarts": 0}
        c._register_pending_actor(spec, "node-1")
        await c.rpc_actor_started("a1", ("127.0.0.1", 4242), "w1")
        await c.rpc_actor_died("a1", "worker exit")
        await c.stop()

    async def phase2():
        c = Controller("sess-dead", persist_path=path)
        await c.start()
        try:
            assert await c.rpc_get_named_actor("gone") is None
            info = await c.rpc_get_actor_info("a1", wait=False)
            assert info["state"] == "DEAD"
        finally:
            await c.stop()

    asyncio.run(phase1())
    asyncio.run(phase2())


def test_live_cluster_controller_restart(tmp_path):
    """End-to-end: real daemon + worker + named actor survive a
    controller restart; the daemon re-registers and the actor is
    callable through the NEW controller."""
    path = str(tmp_path / "gcs.db")
    session = f"restart-{uuid.uuid4().hex[:8]}"

    async def main():
        c1 = Controller(session, persist_path=path)
        addr1 = await c1.start()
        daemon = NodeDaemon(addr1, session, resources={"CPU": 2.0})
        await daemon.start()

        from ray_tpu._private.core import CoreClient, LoopRunner
        client = CoreClient(addr1, daemon.address, session,
                            loop_runner=LoopRunner(
                                asyncio.get_running_loop()))
        await client.async_start()

        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        actor_id, creation_ref = client.create_actor(
            Counter, (), {}, {"name": "ctr"})
        assert await client.aio_get(creation_ref) is None
        ref = client.submit_actor_task(actor_id, "incr", (), {}, {})
        assert await client.aio_get(ref) == 1

        # ---- kill controller, start a new one from the same state ----
        await c1.stop()
        c2 = Controller(session, persist_path=path)
        addr2 = await c2.start(port=addr1[1])   # same port: clients reuse
        assert tuple(addr2) == tuple(addr1)

        # daemon heartbeat re-registers within ~1s
        for _ in range(60):
            await asyncio.sleep(0.25)
            if daemon.node_id in c2.nodes:
                break
        assert daemon.node_id in c2.nodes

        # named actor resolves via the new controller and still has state
        info = await c2.rpc_get_named_actor("ctr")
        assert info is not None and info["actor_id"] == actor_id
        ref2 = client.submit_actor_task(actor_id, "incr", (), {}, {})
        assert await client.aio_get(ref2) == 2   # state survived

        await client._async_shutdown()
        await daemon.stop()
        await c2.stop()

    asyncio.run(main())
    # raw CoreClient bypasses ray_tpu.shutdown(), which owns the
    # session arena's lifecycle — unlink it here or it leaks in /dev/shm
    from ray_tpu._private.object_store import unlink_session_arena
    unlink_session_arena(session)
