"""Dropless MoE (VERDICT r4 weak #7 / SURVEY §2.4 EP target).

Gates: (1) dropless output matches a naive per-token expert-mixture
reference exactly (zero drops by construction, where the capacity path
provably drops); (2) the ep-sharded ragged-exchange path is bit-equal
to the single-shard sort+ragged_dot path; (3) dropless trains end-to-end
on the dp/fsdp/ep virtual-mesh config."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.ops import moe
from ray_tpu.parallel import MeshSpec


def _naive_reference(x, router_w, wi, wg, wd, top_k):
    """Per-token dense mixture: every routed token computes — the
    definition of dropless."""
    b, s, h = x.shape
    xt = np.asarray(x, np.float32).reshape(-1, h)
    probs = np.asarray(moe.router_probs(jnp.asarray(xt),
                                        jnp.asarray(router_w)))
    out = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        order = np.argsort(-probs[t])[:top_k]
        gates = probs[t][order]
        gates = gates / max(gates.sum(), 1e-9)
        for g, e in zip(gates, order):
            gate = np.asarray(jax.nn.silu(
                jnp.asarray(xt[t] @ np.asarray(wg, np.float32)[e])))
            up = xt[t] @ np.asarray(wi, np.float32)[e]
            out[t] += g * ((gate * up) @ np.asarray(wd, np.float32)[e])
    return out.reshape(b, s, h)


def _toy(seed=0, b=2, s=8, h=16, e=4, f=32):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((b, s, h)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((h, e)) * 0.5, jnp.float32)
    wi = jnp.asarray(rng.standard_normal((e, h, f)) * 0.1, jnp.float32)
    wg = jnp.asarray(rng.standard_normal((e, h, f)) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((e, f, h)) * 0.1, jnp.float32)
    return x, router, wi, wg, wd


def test_dropless_matches_naive_reference():
    x, router, wi, wg, wd = _toy()
    out, aux = jax.jit(
        lambda *a: moe.moe_ffn(*a, top_k=2, dropless=True))(
        x, router, wi, wg, wd)
    ref = _naive_reference(x, router, wi, wg, wd, top_k=2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
    assert np.isfinite(float(aux))


def test_capacity_path_drops_where_dropless_does_not():
    """Skewed routing: capacity_factor=1 demonstrably drops tokens
    (output == residual 0 for the dropped ones), dropless never does."""
    x, router, wi, wg, wd = _toy(seed=3)
    # bias the router hard toward expert 0 so capacity overflows
    router = router.at[:, 0].add(8.0)
    cap_out, _ = jax.jit(
        lambda *a: moe.moe_ffn(*a, top_k=1, capacity_factor=1.0))(
        x, router, wi, wg, wd)
    free_out, _ = jax.jit(
        lambda *a: moe.moe_ffn(*a, top_k=1, dropless=True))(
        x, router, wi, wg, wd)
    ref = _naive_reference(x, router, wi, wg, wd, top_k=1)
    np.testing.assert_allclose(np.asarray(free_out), ref,
                               rtol=2e-4, atol=2e-4)
    # the capacity path must differ somewhere (= dropped tokens)
    assert np.abs(np.asarray(cap_out) - ref).max() > 1e-3


def test_dropless_ep_sharded_matches_local():
    """ragged-exchange dispatch over ep=4 == single-shard dispatch."""
    x, router, wi, wg, wd = _toy(b=2, s=16, e=8, f=24)
    local, aux_l = jax.jit(
        lambda *a: moe.moe_ffn(*a, top_k=2, dropless=True))(
        x, router, wi, wg, wd)
    mesh = MeshSpec(dp=2, fsdp=1, sp=1, tp=1, ep=4).build(jax.devices()[:8])
    with jax.set_mesh(mesh):
        ep_out, aux_e = jax.jit(
            lambda *a: moe.moe_ffn(*a, top_k=2, dropless=True,
                                   mesh=mesh))(x, router, wi, wg, wd)
    np.testing.assert_allclose(np.asarray(ep_out), np.asarray(local),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_e), float(aux_l), rtol=1e-6)


def test_dropless_grad_flows():
    x, router, wi, wg, wd = _toy()

    def loss(router, wi, wg, wd):
        out, aux = moe.moe_ffn(x, router, wi, wg, wd,
                               top_k=2, dropless=True)
        return jnp.sum(out ** 2) + 0.01 * aux

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(
        router, wi, wg, wd)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
    # expert weights actually receive gradient
    assert np.abs(np.asarray(grads[1])).max() > 0


def test_dropless_llama_trains_on_ep_mesh():
    """dp/fsdp/ep mesh + moe_dropless llama: loss finite and decreasing
    over a few steps (the dryrun config's training gate)."""
    from ray_tpu.models.training import TrainStepBundle
    cfg = llama.config("debug_moe", moe_dropless=True)
    mesh = MeshSpec(dp=2, fsdp=2, sp=1, tp=1, ep=2).build(jax.devices()[:8])
    bundle = TrainStepBundle(cfg, mesh)
    state = bundle.init_state(0)
    rng = np.random.default_rng(0)
    tokens = bundle.shard_batch(jnp.asarray(
        rng.integers(0, cfg.vocab_size, (4, 64)), jnp.int32))
    losses = []
    for _ in range(4):
        state, metrics = bundle.step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
