"""Autoscaler e2e over REAL daemon processes (VERDICT r3 weak #10): TPU
slice-head gang demand makes the autoscaler exec the CLI join path, the
joined process node serves the placement group, and idle scale-down
kills the process again."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (Autoscaler, AutoscalerConfig, NodeType,
                                ProcessNodeProvider)
from ray_tpu.util.placement_group import placement_group


def test_tpu_gang_demand_joins_real_process_node():
    ray_tpu.init(num_cpus=1)
    provider = ProcessNodeProvider()
    scaler = Autoscaler(provider, AutoscalerConfig(
        node_types=[
            NodeType("tpu-host", {"CPU": 2.0, "TPU": 4.0,
                                  "TPU-v5litepod-8-head": 1.0},
                     max_workers=2)],
        idle_timeout_s=3.0))
    try:
        scaler.start(interval_s=1.0)
        # slice gang demand: infeasible until a TPU host joins
        pg = placement_group(
            [{"TPU": 4.0, "TPU-v5litepod-8-head": 1.0}],
            strategy="STRICT_PACK")
        pg.ready(timeout=90)           # the join actually happened
        # the PG turns ready the moment the node REGISTERS; the
        # provider records it when add_node returns a beat later
        deadline = time.time() + 30
        while time.time() < deadline \
                and not provider.non_terminated_nodes():
            time.sleep(0.5)
        assert provider.non_terminated_nodes(), "no process node"

        @ray_tpu.remote(num_cpus=0, resources={"TPU": 1.0},
                        placement_group=pg)
        def where():
            return ray_tpu.get_runtime_context().get_node_id()

        head_id = ray_tpu.init(ignore_reinit_error=True
                               ).head_daemon.node_id
        node = ray_tpu.get(where.remote(), timeout=60)
        assert node != head_id         # ran on the joined process node

        # release the gang; the idle process node must be terminated
        from ray_tpu.util.placement_group import remove_placement_group
        remove_placement_group(pg)
        deadline = time.time() + 60
        while time.time() < deadline and provider.non_terminated_nodes():
            time.sleep(1.0)
        assert not provider.non_terminated_nodes(), "no scale-down"
    finally:
        scaler.stop()
        ray_tpu.shutdown()
