"""Placement groups: reserve/commit, strategies, task placement, removal.

Modeled on python/ray/tests/test_placement_group*.py."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import PlacementGroupUnavailableError
from ray_tpu.util import (placement_group, remove_placement_group,
                          placement_group_table,
                          PlacementGroupSchedulingStrategy)


def test_pack_pg_reserves_and_schedules(ray_start):
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="PACK")
    assert pg.ready(timeout=30)
    avail = ray_tpu.available_resources()
    assert avail["CPU"] <= 4.0 + 1e-9  # 8 total - 4 reserved

    @ray_tpu.remote(num_cpus=2)
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    strat = PlacementGroupSchedulingStrategy(pg, 0)
    node = ray_tpu.get(where.options(scheduling_strategy=strat).remote(),
                       timeout=60)
    assert node is not None
    remove_placement_group(pg)
    time.sleep(0.3)
    assert ray_tpu.available_resources()["CPU"] >= 7.9


def test_strict_spread_needs_enough_nodes(ray_start):
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    # unsatisfiable on a 1-node cluster — satisfiability cannot
    # change while we wait, so a short timeout keeps the semantics
    with pytest.raises(PlacementGroupUnavailableError):
        pg.ready(timeout=5)

    n1 = ray_tpu.add_fake_node(num_cpus=2)
    n2 = ray_tpu.add_fake_node(num_cpus=2)
    try:
        pg2 = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
        assert pg2.ready(timeout=60)
        table = placement_group_table()
        nodes = {b["node_id"] for b in table[pg2.id]["bundles"]}
        assert len(nodes) == 3, "STRICT_SPREAD must use distinct nodes"
        remove_placement_group(pg2)
    finally:
        ray_tpu.remove_node(n1)
        ray_tpu.remove_node(n2)


def test_strict_pack_one_node(ray_start):
    n1 = ray_tpu.add_fake_node(num_cpus=4, resources={"tag_sp": 1.0})
    try:
        pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK")
        assert pg.ready(timeout=60)
        table = placement_group_table()
        nodes = {b["node_id"] for b in table[pg.id]["bundles"]}
        assert len(nodes) == 1
        remove_placement_group(pg)
    finally:
        ray_tpu.remove_node(n1)


def test_pg_infeasible_raises_on_timeout(ray_start):
    # Infeasible-on-current-nodes PGs stay PENDING (the cluster may still
    # scale up), but ready() surfaces the recorded reason at the deadline.
    pg = placement_group([{"CPU": 512}], strategy="STRICT_PACK")
    with pytest.raises(PlacementGroupUnavailableError):
        pg.ready(timeout=5)


def test_actor_in_pg(ray_start):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=1)
    class A:
        def ping(self):
            return "pong"

    a = A.options(scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
                  ).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    # Removing the PG kills its actors.
    remove_placement_group(pg)
    time.sleep(1.0)
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(a.ping.remote(), timeout=30)


def test_pg_bundle_capacity_enforced(ray_start):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=2)
    def big():
        return 1

    strat = PlacementGroupSchedulingStrategy(pg, 0)
    with pytest.raises(PlacementGroupUnavailableError):
        ray_tpu.get(big.options(scheduling_strategy=strat).remote(),
                    timeout=30)
    remove_placement_group(pg)
