"""LLM engine decode benchmark: continuous-batching tokens/s on one chip.

Prints ONE JSON line per run: {"metric", "value", "unit", "detail"}.
Measures steady-state decode throughput of the native paged-KV engine
(ray_tpu/llm/_internal/engine.py) at a fixed running batch, plus the
per-layer paged-attention decode cost at short vs long context — the
number that shows kernel decode cost scaling with ACTUAL context rather
than max context (VERDICT r1 weak #5).

On TPU the Pallas paged kernel runs compiled; on CPU the dense-gather
path runs (kernel correctness is covered by interpret-mode tests).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def _tpu_bench_model():
    """The ~890M bench model, shared by every sub-benchmark so they
    can never silently measure different models."""
    from ray_tpu.models import llama
    return llama.config("tiny", vocab_size=32000, hidden=2048,
                        n_layers=12, n_heads=16, n_kv_heads=8,
                        head_dim=128, ffn=8192, max_seq=2048)


def bench_engine(on_tpu: bool) -> dict:
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)

    from ray_tpu.models import llama
    if on_tpu:
        cfg = _tpu_bench_model()
        batch, prompt_len, gen = 8, 128, 128
    else:
        cfg = llama.config("debug")
        batch, prompt_len, gen = 4, 16, 16
    ec = EngineConfig(model=cfg, max_batch_size=batch,
                      num_pages=max(256, batch * 32), page_size=16)
    eng = InferenceEngine(ec)
    rng = np.random.default_rng(0)
    reqs = []
    for i in range(batch):
        reqs.append(Request(
            request_id=f"r{i}",
            prompt_tokens=rng.integers(
                1, cfg.vocab_size, prompt_len).tolist(),
            params=SamplingParams(max_tokens=gen)))
        eng.add_request(reqs[-1])
    # Warm up until the whole batch is decoding (all prefills done +
    # first decode compiled) so the timed window is pure decode.
    while any(not r.output_tokens for r in reqs):
        eng.step()
    eng.step()
    before = sum(len(r.output_tokens) for r in reqs)
    t0 = time.perf_counter()
    steps = 0
    while steps < gen - 8 and eng.has_work():
        eng.step()
        steps += 1
    dt = time.perf_counter() - t0
    toks = sum(len(r.output_tokens) for r in reqs) - before
    return {
        "decode_tokens_per_sec": round(toks / dt, 1),
        "decode_step_ms": round(dt / max(steps, 1) * 1e3, 2),
        "batch": batch, "prompt_len": prompt_len,
        "params": cfg.num_params(),
    }


def bench_mixed(on_tpu: bool, smoke: bool = False) -> dict:
    """Mixed prefill+decode throughput (ISSUE 1 headline): bursts of
    prompts land WHILE a batch decodes, so prefilling and decoding
    slots contend for the whole run — the regime where the legacy
    engine serializes prefills one chunk per tick (paying a separate
    whole-batch decode dispatch each time) and the unified ragged step
    packs everything into ONE dispatch under the token budget.
    Records the new rows: steps-per-token and dispatches-per-step.
    token_match is the fraction of requests whose greedy output is
    bit-identical across the two engines — flips are near-tie argmax
    noise (~0.02 logit margins, where the unified step tracks the
    full-forward gold at least as closely as the legacy path)."""
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if smoke:
        # CI contract: tiny and fast (<30 s) regardless of host
        cfg = llama.config("debug")
        batch, plen, n_req, chunk, budget = 4, 48, 10, 16, 64
        burst, every, gen0 = 3, 6, 8
    elif on_tpu:
        cfg = _tpu_bench_model()
        batch, plen, n_req, chunk, budget = 8, 256, 24, 64, 512
        burst, every, gen0 = 6, 10, 48
    else:
        # big enough that compute (not Python overhead) dominates a tick
        cfg = llama.config("tiny", vocab_size=2048, hidden=256,
                           n_layers=4, n_heads=8, n_kv_heads=4,
                           head_dim=32, ffn=1024, max_seq=512)
        batch, plen, n_req, chunk, budget = 8, 112, 24, 16, 256
        burst, every, gen0 = 6, 10, 16
    rng = np.random.default_rng(4)
    lens = [plen + 16 * (i % 3) for i in range(n_req)]
    gens = [gen0 + 8 * (i % 3) for i in range(n_req)]
    prompts = [rng.integers(1, cfg.vocab_size, lens[i]).tolist()
               for i in range(n_req)]

    def run(unified):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=16,
            num_pages=max(512, batch * 32), seed=5,
            max_prefill_tokens=chunk, enable_prefix_caching=False,
            unified_step=unified, max_num_batched_tokens=budget))

        def drive():
            eng._prefill_rr = 0          # identical packing every pass
            reqs = [Request(f"m{i}", list(p),
                            SamplingParams(max_tokens=gens[i]))
                    for i, p in enumerate(prompts)]
            pending = list(reqs)
            steps = 0
            while eng.has_work() or pending:
                if pending and steps % every == 0:
                    for r in pending[:burst]:
                        eng.add_request(r)
                    pending = pending[burst:]
                eng.step()
                steps += 1
            return reqs, steps

        drive()                          # warmup: compiles every bucket
        d0, t0s = eng.dispatches, eng.ticks
        t0 = time.perf_counter()
        reqs, steps = drive()
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {
            "tokens_per_sec": round(toks / dt, 1),
            "steps_per_token": round(steps / toks, 3),
            "dispatches_per_step": round(
                (eng.dispatches - d0) / max(eng.ticks - t0s, 1), 3),
            "steps": steps,
        }, [r.output_tokens for r in reqs]

    unified, out_u = run(True)
    legacy, out_l = run(False)
    return {
        "unified": unified, "legacy": legacy,
        "unified_speedup": round(
            unified["tokens_per_sec"]
            / max(legacy["tokens_per_sec"], 1e-9), 2),
        "token_match": round(
            sum(a == b for a, b in zip(out_u, out_l)) / n_req, 3),
        "batch": batch, "prompt_len": plen, "requests": n_req,
        "chunk": chunk, "token_budget": budget,
    }


def bench_prefix_cache(on_tpu: bool) -> dict:
    """Shared-prefix speedup: time-to-first-token of an identical prompt
    when its prefix KV is cache-hot vs cold (VERDICT r3 #6)."""
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        cfg = _tpu_bench_model()
        prompt_len, chunk = 1024, 256
    else:
        cfg = llama.config("debug")
        prompt_len, chunk = 96, 32
    eng = InferenceEngine(EngineConfig(
        model=cfg, max_batch_size=2, num_pages=256,
        max_prefill_tokens=chunk))
    prompt = np.random.default_rng(1).integers(
        1, cfg.vocab_size, prompt_len).tolist()

    def ttft(rid):
        req = Request(rid, list(prompt), SamplingParams(max_tokens=2))
        eng.add_request(req)
        t0 = time.perf_counter()
        while not req.output_tokens:
            eng.step()
        dt = time.perf_counter() - t0
        while not req.finished:
            eng.step()
        return dt

    ttft("warmup")                       # compiles the cold chunk path
    ttft("warmup-hot")                   # compiles the cache-hit suffix
    eng.allocator.clear_cache()          # cold again (keep compiles)
    cold = ttft("cold")
    hot = ttft("hot")
    return {"ttft_cold_ms": round(cold * 1e3, 2),
            "ttft_cached_ms": round(hot * 1e3, 2),
            "prefix_speedup": round(cold / max(hot, 1e-9), 2),
            "hit_tokens": eng.allocator.cache_hit_tokens,
            "prompt_len": prompt_len}


def bench_kernel_scaling(on_tpu: bool) -> dict:
    """Per-layer decode attention at short vs long cached context with the
    SAME max_pages: if cost scales with max context (dense gather) the two
    times match; kernel times should scale with actual context."""
    from ray_tpu.ops.paged_attention import paged_decode_attention

    if on_tpu:
        B, H, KVH, D = 8, 16, 8, 128
        max_pages = 128                   # max ctx 2048
    else:
        B, H, KVH, D = 2, 4, 2, 64       # interpret mode is slow: tiny
        max_pages = 4
    page_size = 16
    num_pages = B * max_pages + 1
    rng = np.random.default_rng(0)
    k_pages = jnp.asarray(
        rng.normal(size=(num_pages, page_size, KVH, D)), jnp.bfloat16)
    v_pages = jnp.asarray(
        rng.normal(size=(num_pages, page_size, KVH, D)), jnp.bfloat16)
    tables = jnp.asarray(
        np.arange(B * max_pages).reshape(B, max_pages), jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.bfloat16)

    fn = jax.jit(lambda q, k, v, t, s: paged_decode_attention(
        q, k, v, t, s, interpret=not on_tpu))

    def timed(seq_len):
        lens = jnp.full((B,), seq_len, jnp.int32)
        out = fn(q, k_pages, v_pages, tables, lens)
        np.asarray(out)                       # sync
        iters = 20 if on_tpu else 2
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k_pages, v_pages, tables, lens)
        np.asarray(out)
        return (time.perf_counter() - t0) / iters * 1e3

    short = timed(page_size * max(max_pages // 16, 1))
    long = timed(page_size * max_pages)
    return {"short_ctx_ms": round(short, 3), "long_ctx_ms": round(long, 3),
            "long_over_short": round(long / max(short, 1e-9), 2)}


def bench_speculative(on_tpu: bool) -> dict:
    """Greedy decode throughput, speculative vs plain. SELF-draft
    (the target's own weights) pins acceptance near 1.0, isolating the
    structural effect: 2 dispatches per round for ~k tokens vs 1 per
    token. That wins exactly where per-dispatch latency dominates
    (TPU behind the tunnel — see BENCH_CORE per-call overhead); on
    CPU, where compute dominates and the draft doubles it, the row
    goes BELOW 1x by design — both regimes are the honest signal."""
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        target = _tpu_bench_model()
        batch, gen = 4, 96
    else:
        target = llama.config("debug")
        batch, gen = 2, 32
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, target.vocab_size, 32).tolist()
               for _ in range(batch)]

    tparams = llama.init_params(target, jax.random.PRNGKey(5))

    def run(spec):
        # params passed EXPLICITLY to both engines: self-draft is true
        # by construction, not by seed coupling with the engine's init
        eng = InferenceEngine(EngineConfig(
            model=target, max_batch_size=batch, num_pages=256,
            seed=5, enable_prefix_caching=False, speculative=spec),
            params=tparams)
        # full-length warmup: later rounds cross ctx-bucket
        # boundaries and would otherwise compile inside the timed run
        eng.generate([list(p) for p in prompts],
                     SamplingParams(max_tokens=gen))
        t0 = time.perf_counter()
        reqs = eng.generate([list(p) for p in prompts],
                            SamplingParams(max_tokens=gen))
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return round(toks / dt, 1), eng.stats()

    plain_tps, _ = run(None)
    spec_k = int(os.environ.get("RAY_TPU_BENCH_SPEC_K", "4"))
    spec_tps, st = run({"draft_model": target,
                        "draft_params": tparams,
                        "num_speculative_tokens": spec_k})
    return {"plain_tokens_per_sec": plain_tps,
            "spec_tokens_per_sec": spec_tps,
            "spec_speedup": round(spec_tps / max(plain_tps, 1e-9), 2),
            "acceptance_rate": st.get("spec_acceptance_rate"),
            "tokens_per_round": st.get("spec_tokens_per_round")}


def bench_multi_step(on_tpu: bool) -> dict:
    """Greedy decode throughput at decode_steps_per_call = 1 vs K:
    K decode iterations per dispatch amortize the per-call overhead
    that dominates decode on the tunnel (145 ms/call vs ~3 ms compute
    floor measured round 4); on CPU, where dispatch is ~free, the row
    hovers near 1x by design."""
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        target = _tpu_bench_model()
        batch, gen, ksteps = 8, 96, int(os.environ.get(
            "RAY_TPU_BENCH_DECODE_K", "8"))
    else:
        target = llama.config("debug")
        batch, gen, ksteps = 2, 32, 4
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, target.vocab_size, 32).tolist()
               for _ in range(batch)]

    def run(k):
        eng = InferenceEngine(EngineConfig(
            model=target, max_batch_size=batch, num_pages=256, seed=5,
            enable_prefix_caching=False, decode_steps_per_call=k))
        eng.generate([list(p) for p in prompts],
                     SamplingParams(max_tokens=gen))     # warm/compile
        t0 = time.perf_counter()
        reqs = eng.generate([list(p) for p in prompts],
                            SamplingParams(max_tokens=gen))
        dt = time.perf_counter() - t0
        return round(sum(len(r.output_tokens) for r in reqs) / dt, 1)

    single = run(1)
    multi = run(ksteps)
    return {"k": ksteps, "single_tokens_per_sec": single,
            "multi_tokens_per_sec": multi,
            "multi_speedup": round(multi / max(single, 1e-9), 2)}


def main() -> None:
    import sys
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if "--smoke" in sys.argv:
        # CI mode: tiny model, CPU, <30 s — one JSON line whose
        # dispatches_per_step row fails loudly on scheduler regressions
        mixed = bench_mixed(on_tpu, smoke=True)
        print(json.dumps({
            "metric": "llm_mixed_smoke",
            "value": mixed["unified"]["tokens_per_sec"],
            "unit": "tokens_per_sec",
            "detail": mixed,
        }))
        return
    eng = bench_engine(on_tpu)
    mixed = bench_mixed(on_tpu)
    scaling = bench_kernel_scaling(on_tpu)
    prefix = bench_prefix_cache(on_tpu)
    spec = bench_speculative(on_tpu)
    multi = bench_multi_step(on_tpu)
    print(json.dumps({
        "metric": "llm_decode_tokens_per_sec" if on_tpu
                  else "llm_decode_tokens_per_sec_cpu_fallback",
        "value": eng["decode_tokens_per_sec"],
        "unit": "tokens_per_sec",
        "detail": {"device": getattr(dev, "device_kind", str(dev)),
                   **eng, "mixed_prefill_decode": mixed,
                   "paged_kernel_scaling": scaling,
                   "prefix_cache": prefix, "speculative": spec,
                   "multi_step_decode": multi},
    }))


if __name__ == "__main__":
    main()
