"""LLM engine decode benchmark: continuous-batching tokens/s on one chip.

Prints ONE JSON line per run: {"metric", "value", "unit", "detail"}.
Measures steady-state decode throughput of the native paged-KV engine
(ray_tpu/llm/_internal/engine.py) at a fixed running batch, plus the
per-layer paged-attention decode cost at short vs long context — the
number that shows kernel decode cost scaling with ACTUAL context rather
than max context (VERDICT r1 weak #5).

On TPU the Pallas paged kernel runs compiled; on CPU the dense-gather
path runs (kernel correctness is covered by interpret-mode tests).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def _tpu_bench_model():
    """The ~890M bench model, shared by every sub-benchmark so they
    can never silently measure different models."""
    from ray_tpu.models import llama
    return llama.config("tiny", vocab_size=32000, hidden=2048,
                        n_layers=12, n_heads=16, n_kv_heads=8,
                        head_dim=128, ffn=8192, max_seq=2048)


def bench_engine(on_tpu: bool) -> dict:
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)

    from ray_tpu.models import llama
    if on_tpu:
        cfg = _tpu_bench_model()
        batch, prompt_len, gen = 8, 128, 128
    else:
        cfg = llama.config("debug")
        batch, prompt_len, gen = 4, 16, 16
    ec = EngineConfig(model=cfg, max_batch_size=batch,
                      num_pages=max(256, batch * 32), page_size=16)
    eng = InferenceEngine(ec)
    rng = np.random.default_rng(0)
    reqs = []
    for i in range(batch):
        reqs.append(Request(
            request_id=f"r{i}",
            prompt_tokens=rng.integers(
                1, cfg.vocab_size, prompt_len).tolist(),
            params=SamplingParams(max_tokens=gen)))
        eng.add_request(reqs[-1])
    # Warm up until the whole batch is decoding (all prefills done +
    # first decode compiled) so the timed window is pure decode.
    while any(not r.output_tokens for r in reqs):
        eng.step()
    eng.step()
    before = sum(len(r.output_tokens) for r in reqs)
    t0 = time.perf_counter()
    steps = 0
    while steps < gen - 8 and eng.has_work():
        eng.step()
        steps += 1
    dt = time.perf_counter() - t0
    toks = sum(len(r.output_tokens) for r in reqs) - before
    return {
        "decode_tokens_per_sec": round(toks / dt, 1),
        "decode_step_ms": round(dt / max(steps, 1) * 1e3, 2),
        "batch": batch, "prompt_len": prompt_len,
        "params": cfg.num_params(),
    }


def bench_mixed(on_tpu: bool, smoke: bool = False) -> dict:
    """Mixed prefill+decode throughput (ISSUE 1 headline): bursts of
    prompts land WHILE a batch decodes, so prefilling and decoding
    slots contend for the whole run — the regime where the legacy
    engine serializes prefills one chunk per tick (paying a separate
    whole-batch decode dispatch each time) and the unified ragged step
    packs everything into ONE dispatch under the token budget.
    Records the new rows: steps-per-token and dispatches-per-step.
    token_match is the fraction of requests whose greedy output is
    bit-identical across the two engines — flips are near-tie argmax
    noise (~0.02 logit margins, where the unified step tracks the
    full-forward gold at least as closely as the legacy path)."""
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if smoke:
        # CI contract: tiny and fast (<30 s) regardless of host
        cfg = llama.config("debug")
        batch, plen, n_req, chunk, budget = 4, 48, 10, 16, 64
        burst, every, gen0 = 3, 6, 8
    elif on_tpu:
        cfg = _tpu_bench_model()
        batch, plen, n_req, chunk, budget = 8, 256, 24, 64, 512
        burst, every, gen0 = 6, 10, 48
    else:
        # big enough that compute (not Python overhead) dominates a tick
        cfg = llama.config("tiny", vocab_size=2048, hidden=256,
                           n_layers=4, n_heads=8, n_kv_heads=4,
                           head_dim=32, ffn=1024, max_seq=512)
        batch, plen, n_req, chunk, budget = 8, 112, 24, 16, 256
        burst, every, gen0 = 6, 10, 16
    rng = np.random.default_rng(4)
    lens = [plen + 16 * (i % 3) for i in range(n_req)]
    gens = [gen0 + 8 * (i % 3) for i in range(n_req)]
    prompts = [rng.integers(1, cfg.vocab_size, lens[i]).tolist()
               for i in range(n_req)]

    def run(unified):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=16,
            num_pages=max(512, batch * 32), seed=5,
            max_prefill_tokens=chunk, enable_prefix_caching=False,
            unified_step=unified, max_num_batched_tokens=budget))

        def drive():
            eng._prefill_rr = 0          # identical packing every pass
            reqs = [Request(f"m{i}", list(p),
                            SamplingParams(max_tokens=gens[i]))
                    for i, p in enumerate(prompts)]
            pending = list(reqs)
            steps = 0
            while eng.has_work() or pending:
                if pending and steps % every == 0:
                    for r in pending[:burst]:
                        eng.add_request(r)
                    pending = pending[burst:]
                eng.step()
                steps += 1
            return reqs, steps

        drive()                          # warmup: compiles every bucket
        d0, t0s = eng.dispatches, eng.ticks
        t0 = time.perf_counter()
        reqs, steps = drive()
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {
            "tokens_per_sec": round(toks / dt, 1),
            "steps_per_token": round(steps / toks, 3),
            "dispatches_per_step": round(
                (eng.dispatches - d0) / max(eng.ticks - t0s, 1), 3),
            "steps": steps,
        }, [r.output_tokens for r in reqs]

    unified, out_u = run(True)
    legacy, out_l = run(False)
    return {
        "unified": unified, "legacy": legacy,
        "unified_speedup": round(
            unified["tokens_per_sec"]
            / max(legacy["tokens_per_sec"], 1e-9), 2),
        "token_match": round(
            sum(a == b for a, b in zip(out_u, out_l)) / n_req, 3),
        "batch": batch, "prompt_len": plen, "requests": n_req,
        "chunk": chunk, "token_budget": budget,
    }


def bench_async_ab(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 4 A/B: pipelined async readback vs synchronous folds on
    the bursty mixed prefill+decode workload — the regime with both
    steady decode runs (where the pipeline overlaps host folds with
    device compute) and constant structural events (where it drains).
    Greedy, so the async engine must be TOKEN-EXACT vs sync: the
    one-tick lag only delays when tokens become host-visible, never
    what they are. Reports tokens/s each way plus the async engine's
    tick_times telemetry (overlap_ratio = share of tick wall-time NOT
    blocked on the device readback). In --smoke mode this asserts
    exactness and a never-materially-slower tripwire."""
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if smoke:
        cfg = llama.config("debug")
        batch, plen, n_req, chunk, budget = 4, 48, 10, 16, 64
        burst, every, gen0 = 3, 6, 8
    elif on_tpu:
        cfg = _tpu_bench_model()
        batch, plen, n_req, chunk, budget = 8, 256, 24, 64, 512
        burst, every, gen0 = 6, 10, 48
    else:
        cfg = llama.config("tiny", vocab_size=2048, hidden=256,
                           n_layers=4, n_heads=8, n_kv_heads=4,
                           head_dim=32, ffn=1024, max_seq=512)
        batch, plen, n_req, chunk, budget = 8, 112, 24, 16, 256
        burst, every, gen0 = 6, 10, 16
    rng = np.random.default_rng(8)
    lens = [plen + 16 * (i % 3) for i in range(n_req)]
    gens = [gen0 + 8 * (i % 3) for i in range(n_req)]
    prompts = [rng.integers(1, cfg.vocab_size, lens[i]).tolist()
               for i in range(n_req)]

    def run(async_readback):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=16,
            num_pages=max(512, batch * 32), seed=5,
            max_prefill_tokens=chunk, enable_prefix_caching=False,
            max_num_batched_tokens=budget,
            async_readback=async_readback))

        def drive():
            eng._prefill_rr = 0          # identical packing every pass
            reqs = [Request(f"a{i}", list(p),
                            SamplingParams(max_tokens=gens[i]))
                    for i, p in enumerate(prompts)]
            pending = list(reqs)
            steps = 0
            while eng.has_work() or pending:
                if pending and steps % every == 0:
                    for r in pending[:burst]:
                        eng.add_request(r)
                    pending = pending[burst:]
                eng.step()
                steps += 1
            return reqs, steps

        drive()                          # warmup: compiles every bucket
        # align the GC phase before timing: cyclic collection points
        # are deterministic in allocation counts, so WITHOUT this an
        # unrelated upstream code change can shift a ~100 ms gen-2
        # pass (the jax object graph is big) into exactly one arm of
        # the A/B and fake a 0.6x "regression" at smoke sizes
        import gc
        gc.collect()
        t0 = time.perf_counter()
        reqs, steps = drive()
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {"tokens_per_sec": round(toks / dt, 1), "steps": steps,
                "tick_times": eng.stats()["tick_times"]}, \
            [r.output_tokens for r in reqs]

    async_row, out_a = run(True)
    sync_row, out_s = run(False)
    res = {
        "async": async_row, "sync": sync_row,
        "async_speedup": round(
            async_row["tokens_per_sec"]
            / max(sync_row["tokens_per_sec"], 1e-9), 2),
        "token_exact": out_a == out_s,
        "batch": batch, "requests": n_req, "chunk": chunk,
    }
    if smoke:
        assert res["token_exact"], \
            f"async decode diverged from sync: {out_a} vs {out_s}"
        assert async_row["tick_times"]["lagged_ticks"] > 0, \
            "async engine never pipelined a tick"
        # regression tripwire with slack for CI timer noise: the
        # pipeline must never make decode materially slower
        assert res["async_speedup"] >= 0.8, res
    return res


def bench_telemetry(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 5 gate, two halves. Correctness: after a bursty mixed
    run, /metrics must render with TTFT observations == finished
    requests and ITL observations == generated tokens minus first
    tokens (every token the engine folded is accounted exactly once).
    Overhead: the identical workload with enable_metrics=False is the
    baseline — instrumentation is host-only Python on the fold path
    (the dispatch-guard suite separately proves zero transfers /
    compiles), so the instrumented run must not be slower beyond
    timer noise. In --smoke mode both halves assert."""
    import re
    import uuid

    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if on_tpu and not smoke:
        cfg = _tpu_bench_model()
        batch, plen, n_req, chunk, budget = 8, 256, 24, 64, 512
        burst, every, gen0 = 6, 10, 48
    else:
        cfg = llama.config("debug")
        batch, plen, n_req, chunk, budget = 4, 48, 10, 16, 64
        burst, every, gen0 = 3, 6, 8
    rng = np.random.default_rng(11)
    lens = [plen + 16 * (i % 3) for i in range(n_req)]
    gens = [gen0 + 8 * (i % 3) for i in range(n_req)]
    prompts = [rng.integers(1, cfg.vocab_size, lens[i]).tolist()
               for i in range(n_req)]

    def run(enable_metrics):
        tag = f"bench{uuid.uuid4().hex[:8]}"
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=16,
            num_pages=max(512, batch * 32), seed=5,
            max_prefill_tokens=chunk, enable_prefix_caching=False,
            max_num_batched_tokens=budget,
            enable_metrics=enable_metrics, metrics_model_id=tag))

        def drive():
            eng._prefill_rr = 0
            reqs = [Request(f"t{uuid.uuid4().hex[:6]}", list(p),
                            SamplingParams(max_tokens=gens[i]))
                    for i, p in enumerate(prompts)]
            pending = list(reqs)
            steps = 0
            while eng.has_work() or pending:
                if pending and steps % every == 0:
                    for r in pending[:burst]:
                        eng.add_request(r)
                    pending = pending[burst:]
                eng.step()
                steps += 1
            return reqs

        drive()                          # warmup: compiles every bucket
        t0 = time.perf_counter()
        reqs = drive()
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {"tokens_per_sec": round(toks / dt, 1)}, eng, tag

    on_row, eng_on, tag = run(True)
    off_row, _, _ = run(False)

    def sample(text, name, **tags):
        for line in text.splitlines():
            m = re.match(r"^([a-zA-Z0-9_]+)(?:\{(.*)\})? (.+)$", line)
            if m is None or m.group(1) != name:
                continue
            got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(2) or ""))
            if got == {k: str(v) for k, v in tags.items()}:
                return float(m.group(3))
        return None

    text = eng_on.prometheus_metrics()
    s = eng_on.stats()["requests"]
    finished = sum(s["finished"].values())
    ttft = sample(text, "ray_tpu_llm_ttft_seconds_count", model=tag)
    itl = sample(text, "ray_tpu_llm_itl_seconds_count", model=tag)
    res = {
        "metrics_on": on_row, "metrics_off": off_row,
        "overhead_ratio": round(
            on_row["tokens_per_sec"]
            / max(off_row["tokens_per_sec"], 1e-9), 3),
        "renders": bool(text) and ttft is not None,
        "finished_requests": finished,
        "generated_tokens": s["generated_tokens"],
        "ttft_count": ttft, "itl_count": itl,
        "ttft_count_ok": ttft == finished,
        "itl_count_ok": itl == s["generated_tokens"] - finished,
    }
    if smoke:
        assert res["renders"], "metrics exposition failed to render"
        assert res["ttft_count_ok"], res
        assert res["itl_count_ok"], res
        # tripwire with slack for CI timer noise: host-only recording
        # must never make decode materially slower
        assert res["overhead_ratio"] >= 0.8, res
    return res


def bench_perf_accounting(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 11 gate, three parts.

    Self-consistency: a single-request sync run's analytic totals must
    equal the closed form replayed from the known composition (one
    full-prompt prefill + G-1 decode ticks at growing context) — the
    accounting can't drift from the costs it claims to sum. And the
    rolling summary must be sane: flops > 0, 0 < MFU <= 1 against the
    envelope, a roof named.

    Overhead: the bursty mixed workload with
    enable_perf_accounting=False as baseline — accounting is a handful
    of host multiplies per tick, so the A/B must be ~1.0x (the
    dispatch-guard suite separately proves zero transfers/compiles).

    Regression gate: the canonical perfdiff workload's fingerprint
    (exact closed-form costs + deterministic dispatch mix and token
    totals) must match the committed PERF_BASELINE.json; noisy rates
    are checked against their wide bands. In --smoke mode all three
    assert."""
    import uuid

    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.llm._internal.perfmodel import CostModel
    from ray_tpu.models import llama
    from tools import perfdiff

    if on_tpu and not smoke:
        cfg = _tpu_bench_model()
        batch, plen, n_req, chunk, budget = 8, 256, 24, 64, 512
        burst, every, gen0 = 6, 10, 48
    else:
        cfg = llama.config("debug")
        batch, plen, n_req, chunk, budget = 4, 48, 10, 16, 64
        burst, every, gen0 = 3, 6, 8

    # -- part 1: closed-form self-consistency (sync, one request) ------
    P, G = 24, 12
    eng1 = InferenceEngine(EngineConfig(
        model=cfg, max_batch_size=2, page_size=16, num_pages=64,
        max_prefill_tokens=max(P, chunk), seed=3,
        enable_prefix_caching=False, async_readback=False,
        metrics_model_id=f"perf{uuid.uuid4().hex[:8]}"))
    rng = np.random.default_rng(17)
    r1 = Request("pa0", rng.integers(1, cfg.vocab_size, P).tolist(),
                 SamplingParams(max_tokens=G))
    eng1.add_request(r1)
    while eng1.has_work():
        eng1.step()
    tot = eng1.stats()["perf"]["totals"]
    cm = CostModel(cfg, page_size=16)
    expect = {"flops_gemm": 0.0, "flops_attn": 0.0,
              "bytes_kv_read": 0.0, "bytes_kv_write": 0.0}
    for k, v in cm.chunk_cost(0, P).items():
        expect[k] += v
    for i in range(G - 1):                 # decode at growing context
        for k, v in cm.decode_cost(P + 1 + i).items():
            expect[k] += v
    closed_form_ok = (
        abs(tot["flops_gemm"] - expect["flops_gemm"]) < 1e-3
        and abs(tot["flops_attn"] - expect["flops_attn"]) < 1e-3
        and abs(tot["bytes_kv_read"] - expect["bytes_kv_read"]) < 1e-3
        and abs(tot["bytes_kv_write"] - expect["bytes_kv_write"]) < 1e-3)
    perf1 = eng1.stats()["perf"]

    # -- part 2: accounting-on vs -off overhead A/B --------------------
    rng = np.random.default_rng(11)
    lens = [plen + 16 * (i % 3) for i in range(n_req)]
    gens = [gen0 + 8 * (i % 3) for i in range(n_req)]
    prompts = [rng.integers(1, cfg.vocab_size, lens[i]).tolist()
               for i in range(n_req)]

    def run(enable_perf):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=16,
            num_pages=max(512, batch * 32), seed=5,
            max_prefill_tokens=chunk, enable_prefix_caching=False,
            max_num_batched_tokens=budget,
            enable_perf_accounting=enable_perf,
            # the ISSUE 13 planes ride the accounting hooks but are
            # NOT what this gate measures — bench_attribution holds
            # their own on/off A/B (and the anomaly detector's
            # auto-capture must not tax a timed arm)
            enable_attribution=False,
            enable_anomaly_detection=False,
            metrics_model_id=f"perf{uuid.uuid4().hex[:8]}"))

        def drive():
            eng._prefill_rr = 0
            reqs = [Request(f"p{uuid.uuid4().hex[:6]}", list(p),
                            SamplingParams(max_tokens=gens[i]))
                    for i, p in enumerate(prompts)]
            pending = list(reqs)
            steps = 0
            while eng.has_work() or pending:
                if pending and steps % every == 0:
                    for r in pending[:burst]:
                        eng.add_request(r)
                    pending = pending[burst:]
                eng.step()
                steps += 1
            return reqs

        drive()                          # warmup: compiles every bucket
        import gc
        gc.collect()                     # align GC (see bench_async_ab)
        t0 = time.perf_counter()
        reqs = drive()
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {"tokens_per_sec": round(toks / dt, 1)}, eng

    on_row, eng_on = run(True)
    off_row, eng_off = run(False)
    perf_on = eng_on.stats()["perf"]

    # -- part 3: fingerprint vs the committed baseline -----------------
    fingerprint = perfdiff.run_canonical_workload()
    try:
        baseline = perfdiff.load_baseline()
        diff_failures = perfdiff.compare(baseline, fingerprint)
    except FileNotFoundError:
        baseline, diff_failures = None, ["baseline file missing"]

    res = {
        "accounting_on": on_row, "accounting_off": off_row,
        "overhead_ratio": round(
            on_row["tokens_per_sec"]
            / max(off_row["tokens_per_sec"], 1e-9), 3),
        "closed_form_ok": closed_form_ok,
        "flops_total": tot["flops"],
        "mfu": perf_on["mfu"], "mbu": perf_on["mbu"],
        "roof": perf_on["roof"], "envelope": perf_on["envelope"],
        "decode_tokens_per_s": perf_on["decode_tokens_per_s"],
        "single_request_perf": {k: perf1[k] for k in
                                ("mfu", "mbu", "roof")},
        "accounting_off_disabled": (
            eng_off.stats()["perf"].get("enabled") is False),
        "fingerprint": fingerprint,
        "perfdiff_failures": diff_failures,
    }
    if smoke:
        assert res["closed_form_ok"], (tot, expect)
        assert res["flops_total"] > 0, res
        assert 0 < res["mfu"] <= 1.0, res
        assert 0 < res["mbu"] <= 1.0, res
        assert res["roof"] in ("compute", "memory"), res
        assert res["accounting_off_disabled"], res
        # tripwire with slack for CI timer noise: per-tick host
        # arithmetic must never make decode materially slower
        assert res["overhead_ratio"] >= 0.8, res
        assert not diff_failures, diff_failures
    return res


def bench_quant_ab(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 16 gate: quantized-vs-f32 serving A/B.

    Three surfaces, each with its own tolerance discipline:

    Bytes (exact): int8 pages + per-(row, head) f32 scales must cut
    the per-page device footprint and the cost model's KV read bytes
    by >= 1.9x vs a TRUE f32 baseline (the debug config's bf16
    activations are pinned to f32 for the A/B so the ratio means what
    the ISSUE says).

    Logprobs (bounded): one model-level ragged prefill over identical
    pools, f32 vs quantized — max |delta log-softmax| over valid rows
    must stay inside the per-kind band (int8 tight, fp8 loose: e4m3
    carries ~3 mantissa bits).

    Tokens (statistical): greedy engine A/B on a random-weight debug
    model. Near-tied logits mean a single early flip cascades down
    the whole stream, so agreement is gated LOOSELY per kind while
    FIRST tokens (prefill-dominated, no compounding) are gated tight.
    Throughput may pay the CPU gather-path dequant tax but must not
    collapse (the fused-dequant win is a TPU bandwidth effect the CPU
    tier cannot see)."""
    import dataclasses
    import uuid

    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.llm._internal.perfmodel import CostModel
    from ray_tpu.models import llama
    from ray_tpu.models.llama import LlamaConfig

    # -- part 1: model-level logprob delta bound -----------------------
    from ray_tpu.models.llama_infer import ragged_forward
    from ray_tpu.ops import kv_quant
    from ray_tpu.ops.paged_attention import scatter_kv, scatter_kv_quant

    mcfg = LlamaConfig(vocab_size=64, hidden=32, n_layers=2, n_heads=4,
                       n_kv_heads=2, head_dim=8, ffn=64, max_seq=64)
    params = llama.init_params(mcfg, jax.random.PRNGKey(0))
    L, KVH, D = mcfg.n_layers, mcfg.n_kv_heads, mcfg.head_dim
    n_pages, page = 8, 4
    rng = np.random.default_rng(1)
    T = 8
    tokens = jnp.asarray(rng.integers(0, 64, size=T).astype(np.int32))
    slot_ids = jnp.asarray(np.array([0] * 5 + [1, 0, 0], np.int32))
    positions = jnp.asarray(np.array([0, 1, 2, 3, 4, 3, 0, 0],
                                     np.int32))
    valid = jnp.asarray(np.array([1, 1, 1, 1, 1, 1, 0, 0], bool))
    start = jnp.asarray(np.array([0, 3], np.int32))
    last_idx = jnp.asarray(np.array([4, 5], np.int32))
    tables = jnp.asarray(np.array([[0, 1, 2], [3, 4, 5]], np.int32))
    ctx = jnp.asarray(rng.normal(size=(3, L, KVH, D))
                      .astype(np.float32) * 0.5)
    pos3 = jnp.asarray(np.array([0, 1, 2], np.int32))
    tb3 = jnp.tile(tables[1], (3, 1))
    val3 = jnp.ones(3, bool)

    kf = jnp.zeros((L, n_pages, page, KVH, D), jnp.float32)
    kf, vf = scatter_kv(kf, jnp.zeros_like(kf), ctx, ctx, tb3, pos3,
                        val3)
    lf, _, _ = ragged_forward(mcfg, params, tokens, slot_ids,
                              positions, valid, start, last_idx, kf,
                              vf, tables, impl="gather")
    lp_f = jax.nn.log_softmax(lf, axis=-1)
    logprob_delta = {}
    for kind in ("int8", "fp8"):
        kq = jnp.zeros((L, n_pages, page, KVH, D),
                       kv_quant.storage_dtype(kind))
        ks = jnp.zeros((L, n_pages, page, KVH), jnp.float32)
        kq, vq, ks, vs = scatter_kv_quant(
            kq, jnp.zeros_like(kq), ks, jnp.zeros_like(ks), ctx, ctx,
            tb3, pos3, val3, kind)
        lq, *_ = ragged_forward(mcfg, params, tokens, slot_ids,
                                positions, valid, start, last_idx, kq,
                                vq, tables, impl="gather",
                                kv_kind=kind, k_scales=ks,
                                v_scales=vs)
        lp_q = jax.nn.log_softmax(lq, axis=-1)
        # logits are per SLOT (each slot's last valid token; both
        # slots here hold valid work)
        delta = jnp.max(jnp.abs(lp_q - lp_f))
        logprob_delta[kind] = round(float(delta), 4)

    # -- part 2: engine greedy A/B + byte accounting -------------------
    cfg = dataclasses.replace(llama.config("debug"),
                              dtype=jnp.float32)
    batch, plen, gen, n_req = 4, 24, 24, 8
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size, plen).tolist()
               for _ in range(n_req)]

    def run(kind):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, num_pages=256,
            page_size=16, kv_dtype=kind, seed=11,
            metrics_model_id=f"qab{uuid.uuid4().hex[:8]}"))

        def drive(tag):
            reqs = [Request(f"{tag}{i}", list(p),
                            SamplingParams(max_tokens=gen))
                    for i, p in enumerate(prompts)]
            for r in reqs:
                eng.add_request(r)
            while eng.has_work():
                eng.step()
            return reqs

        reqs = drive("w")                # warmup run (compiles)
        t0 = time.perf_counter()
        timed = drive("t")
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in timed)
        return reqs, round(toks / dt, 1), eng.stats()

    f32_reqs, f32_tps, f32_st = run("f32")
    cm_f32 = CostModel(cfg, page_size=16)
    res = {"logprob_delta": logprob_delta,
           "f32_tokens_per_sec": f32_tps,
           "f32_page_bytes": f32_st["kv_page_bytes"]}
    for kind in ("int8", "fp8"):
        qreqs, qtps, qst = run(kind)
        agree = sum(
            sum(a == b for a, b in zip(x.output_tokens,
                                       y.output_tokens))
            for x, y in zip(f32_reqs, qreqs))
        total = sum(len(x.output_tokens) for x in f32_reqs)
        first = sum(x.output_tokens[0] == y.output_tokens[0]
                    for x, y in zip(f32_reqs, qreqs))
        cm_q = CostModel(cfg, page_size=16, kv_dtype=kind)
        res[kind] = {
            "tokens_per_sec": qtps,
            "tps_ratio_vs_f32": round(qtps / max(f32_tps, 1e-9), 3),
            "token_agreement": round(agree / max(total, 1), 3),
            "first_token_agreement": round(first / n_req, 3),
            "page_bytes": qst["kv_page_bytes"],
            "footprint_ratio": round(
                f32_st["kv_page_bytes"] / qst["kv_page_bytes"], 2),
            "kv_read_bytes_ratio": round(
                cm_f32.kv_bytes_per_token / cm_q.kv_bytes_per_token,
                2),
            "dispatches_per_step": qst["dispatches_per_step"],
        }
    if smoke:
        # bytes: exact arithmetic, the headline perf_opt claim
        for kind in ("int8", "fp8"):
            assert res[kind]["footprint_ratio"] >= 1.9, res[kind]
            assert res[kind]["kv_read_bytes_ratio"] >= 1.9, res[kind]
            assert res[kind]["dispatches_per_step"] == 1.0, res[kind]
        # logprobs: per-kind bands (calibrated at ~2x observed)
        assert res["logprob_delta"]["int8"] <= 0.25, res
        assert res["logprob_delta"]["fp8"] <= 0.80, res
        # tokens: loose stream agreement (flips cascade), tight first
        # tokens (prefill-dominated, no compounding)
        assert res["int8"]["token_agreement"] >= 0.55, res["int8"]
        assert res["fp8"]["token_agreement"] >= 0.35, res["fp8"]
        assert res["int8"]["first_token_agreement"] >= 0.75, res
        assert res["fp8"]["first_token_agreement"] >= 0.75, res
        # throughput gates only where the fused kernel runs: the CPU
        # smoke uses the XLA gather fallback whose whole-context
        # dequant tax is exactly what the Pallas kernel deletes, and
        # this shared VM's ambient load swings the ratio several x
        if on_tpu:
            assert res["int8"]["tps_ratio_vs_f32"] >= 0.6, res["int8"]
    return res


def bench_attribution(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 13 gate, two halves.

    Conservation: a bursty mixed prefill+decode workload with spills
    (half-capacity pages, offload on), greedy AND sampled rows — the
    summed per-request receipts must equal the PerfAccountant's tick
    totals EXACTLY (closed form, not banded) for every conserved
    field, and every request must end with a closed receipt.

    Overhead: the same workload with attribution + anomaly detection
    OFF as baseline (perf accounting stays ON in both arms, so the
    A/B isolates the ISSUE 13 cost: a dict update per slot per tick
    and a few float ops for the detector). Must be ~1.0x; the
    dispatch-guard suite separately proves zero transfers/compiles
    with both features enabled. The detector's auto-capture reactions
    (profile arming / black-box dump) are disabled in BOTH arms: they
    run only on ticks that already went anomalous — deliberately
    expensive evidence-gathering, exercised by the anomaly e2e test —
    so they are not part of the steady-state overhead contract."""
    import uuid

    from ray_tpu.llm._internal.attribution import CONSERVED_FIELDS
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine, Request,
                                              SamplingParams)
    from ray_tpu.models import llama

    if on_tpu and not smoke:
        cfg = _tpu_bench_model()
        batch, plen, n_req, gen0 = 8, 192, 18, 48
    else:
        cfg = llama.config("debug")
        batch, plen, n_req, gen0 = 3, 40, 12, 16

    rng = np.random.default_rng(23)
    prompts = [rng.integers(1, cfg.vocab_size,
                            plen + 8 * (i % 3)).tolist()
               for i in range(n_req)]

    def run(enable):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=8,
            # roughly HALF the workload's worst-case page demand:
            # spills/restores are exercised, so d2h/h2d attribution
            # is part of the conservation sum
            num_pages=max(
                batch * (plen + 8 + gen0 + 8) // 8 // 2, 16),
            seed=7, max_prefill_tokens=16, kv_watermark_tokens=8,
            enable_kv_offload=True, enable_prefix_caching=False,
            enable_attribution=enable,
            enable_anomaly_detection=enable,
            anomaly={"auto_profile": False, "auto_dump": False},
            metrics_model_id=f"attr{uuid.uuid4().hex[:8]}"))

        def drive():
            reqs = [Request(
                f"a{uuid.uuid4().hex[:6]}", list(p),
                SamplingParams(
                    max_tokens=gen0 + 8 * (i % 2),
                    temperature=0.8 if i % 2 else 0.0,
                    top_k=20 if i % 2 else 0),
                tenant="tenant-b" if i % 3 == 0 else "")
                    for i, p in enumerate(prompts)]
            pending = list(reqs)
            steps = 0
            while eng.has_work() or pending:
                if pending and steps % 5 == 0:
                    for r in pending[:3]:
                        eng.add_request(r)
                    pending = pending[3:]
                eng.step()
                steps += 1
            return reqs

        drive()                          # warmup compiles
        import gc
        gc.collect()                     # align GC (see bench_async_ab)
        t0 = time.perf_counter()
        reqs = drive()
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {"tokens_per_sec": round(toks / dt, 1)}, eng

    on_row, eng_on = run(True)
    off_row, eng_off = run(False)

    perf_tot = eng_on.perf.totals()
    attrib_tot = eng_on.attrib.totals()
    mismatches = [k for k, _ in CONSERVED_FIELDS
                  if perf_tot[k] != attrib_tot[k]]
    summ = eng_on.attrib.summary()
    res = {
        "attribution_on": on_row, "attribution_off": off_row,
        "overhead_ratio": round(
            on_row["tokens_per_sec"]
            / max(off_row["tokens_per_sec"], 1e-9), 3),
        "conserved": not mismatches,
        "conservation_mismatches": mismatches,
        "spills": eng_on.host_tier.spills_total,
        "receipts_finished": summ["requests_total"] - summ["live"],
        "live_receipts": summ["live"],
        "tenants": sorted(summ["tenants"]),
        "anomaly_ticks": eng_on.anomaly.stats()["ticks"],
        "attribution_off_disabled": (
            eng_off.stats()["attribution"].get("enabled") is False),
    }
    if smoke:
        assert res["conserved"], (
            "receipt conservation failed", mismatches,
            {k: (perf_tot[k], attrib_tot[k])
             for k, _ in CONSERVED_FIELDS})
        assert res["spills"] >= 1, res      # the gate covered spills
        assert res["live_receipts"] == 0, res
        assert set(res["tenants"]) == {"default", "tenant-b"}, res
        assert res["anomaly_ticks"] > 0, res
        assert res["attribution_off_disabled"], res
        # tripwire with CI-noise slack: per-slot dict arithmetic must
        # never make decode materially slower
        assert res["overhead_ratio"] >= 0.8, res
    return res


def bench_kernel_tick(on_tpu: bool) -> dict:
    """ISSUE 2 smoke gate: drive a small mixed workload through the
    unified engine with decode_impl=pallas_interpret (the Pallas
    ragged kernel in interpreter mode — unified ticks AND pure-decode
    ticks both run kernels) and require token-exact greedy output vs
    the dense gather engine. Asserts (CI fails loudly)."""
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    cfg = llama.config("debug")
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
               for n in (24, 9, 1)]

    def run(impl):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=3, page_size=8, num_pages=64,
            prefill_buckets=(16, 32), max_prefill_tokens=16, seed=5,
            enable_prefix_caching=False, decode_impl=impl))
        reqs = [Request(f"k{i}", list(p), SamplingParams(max_tokens=4))
                for i, p in enumerate(prompts)]
        for r in reqs:
            eng.add_request(r)
        ticks = 0
        while eng.has_work():
            eng.step()
            ticks += 1
        return [r.output_tokens for r in reqs], ticks

    out_g, _ = run("gather")
    out_k, ticks = run("pallas_interpret")
    exact = out_g == out_k
    assert exact, f"kernel tick diverged: {out_k} vs {out_g}"
    return {"token_exact": exact, "ticks": ticks,
            "impl": "pallas_interpret"}


def bench_long_ctx(on_tpu: bool) -> dict:
    """ISSUE 2 headline: bursty mixed prefill+decode at multi-
    thousand-token contexts, gather vs Pallas ragged kernel. This is
    the regime where the gather path's per-layer transient —
    T x ctx x KVH x D floats of per-token gathered context — is the
    dominant memory term and the kernel streams pages instead (its
    staging is O(B x chunk x H x D)). Reports tokens/s per impl plus
    the peak per-layer attention transient each path materializes.

    On CPU the kernel runs in interpreter mode (Python-speed grid
    steps), so shapes shrink and kernel tokens/s is NOT a hardware
    number — transient sizes and token agreement are the CPU signal;
    run on TPU for the real A/B.
    """
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        cfg = _tpu_bench_model()              # max_seq 2048
        batch, plen, n_req, chunk, budget = 8, 1792, 12, 256, 512
        gen = 32
        kernel_impl = "pallas"
    else:
        cfg = llama.config("tiny", vocab_size=512, hidden=128,
                           n_layers=2, n_heads=4, n_kv_heads=2,
                           head_dim=32, ffn=256, max_seq=2048)
        batch, plen, n_req, chunk, budget = 2, 1024, 3, 64, 96
        gen = 4
        kernel_impl = "pallas_interpret"
    page = 16
    rng = np.random.default_rng(9)
    prompts = [rng.integers(1, cfg.vocab_size,
                            plen + 64 * (i % 3)).tolist()
               for i in range(n_req)]

    def run(impl):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=page,
            num_pages=max(512, batch * 192), seed=5,
            max_prefill_tokens=chunk, enable_prefix_caching=False,
            max_num_batched_tokens=budget, decode_impl=impl))
        reqs = [Request(f"L{i}", list(p),
                        SamplingParams(max_tokens=gen))
                for i, p in enumerate(prompts)]
        pending = list(reqs)
        t0 = time.perf_counter()
        steps = 0
        while eng.has_work() or pending:
            if pending and steps % 4 == 0:
                for r in pending[:batch // 2 or 1]:
                    eng.add_request(r)
                pending = pending[batch // 2 or 1:]
            eng.step()
            steps += 1
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {"tokens_per_sec": round(toks / dt, 2),
                "wall_s": round(dt, 1), "steps": steps}, \
            [r.output_tokens for r in reqs]

    gather, out_g = run("gather")
    kernel, out_k = run(kernel_impl)

    # peak per-layer attention transient (bytes), analytic: the gather
    # path materializes k_ctx[slot_ids] + v_ctx[slot_ids] in f32; the
    # kernel stages padded per-slot Q/O/new-KV in model dtype and
    # streams context pages through a fixed VMEM block
    from ray_tpu.ops.ragged_paged_attention import DEFAULT_Q_BLOCK
    t_bucket = 1 << max(budget - 1, 1).bit_length()
    max_ctx_tokens = -(-cfg.max_seq // page) * page
    kvh, h, d = cfg.n_kv_heads, cfg.n_heads, cfg.head_dim
    dt_bytes = jnp.dtype(cfg.dtype).itemsize
    gather_bytes = 2 * t_bucket * max_ctx_tokens * kvh * d * 4
    qb = DEFAULT_Q_BLOCK
    qp = -(-min(t_bucket, chunk) // qb) * qb
    kernel_bytes = (batch + 1) * qp * (h + 2 * kvh) * d * dt_bytes
    return {
        "gather": gather, "kernel": kernel,
        "kernel_impl": kernel_impl,
        "kernel_speedup": round(
            kernel["tokens_per_sec"]
            / max(gather["tokens_per_sec"], 1e-9), 2),
        "token_match": round(
            sum(a == b for a, b in zip(out_g, out_k)) / n_req, 3),
        "peak_attn_transient_bytes": {
            "gather": gather_bytes, "kernel": kernel_bytes,
            "ratio": round(gather_bytes / max(kernel_bytes, 1), 1)},
        "batch": batch, "prompt_len": plen, "requests": n_req,
        "chunk": chunk, "token_budget": budget,
    }


def bench_prefix_cache(on_tpu: bool) -> dict:
    """Shared-prefix speedup: time-to-first-token of an identical prompt
    when its prefix KV is cache-hot vs cold (VERDICT r3 #6)."""
    from ray_tpu.llm._internal.engine import (EngineConfig, InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        cfg = _tpu_bench_model()
        prompt_len, chunk = 1024, 256
    else:
        cfg = llama.config("debug")
        prompt_len, chunk = 96, 32
    eng = InferenceEngine(EngineConfig(
        model=cfg, max_batch_size=2, num_pages=256,
        max_prefill_tokens=chunk))
    prompt = np.random.default_rng(1).integers(
        1, cfg.vocab_size, prompt_len).tolist()

    def ttft(rid):
        req = Request(rid, list(prompt), SamplingParams(max_tokens=2))
        eng.add_request(req)
        t0 = time.perf_counter()
        while not req.output_tokens:
            eng.step()
        dt = time.perf_counter() - t0
        while not req.finished:
            eng.step()
        return dt

    ttft("warmup")                       # compiles the cold chunk path
    ttft("warmup-hot")                   # compiles the cache-hit suffix
    eng.allocator.clear_cache()          # cold again (keep compiles)
    cold = ttft("cold")
    hot = ttft("hot")
    return {"ttft_cold_ms": round(cold * 1e3, 2),
            "ttft_cached_ms": round(hot * 1e3, 2),
            "prefix_speedup": round(cold / max(hot, 1e-9), 2),
            "hit_tokens": eng.allocator.cache_hit_tokens,
            "prompt_len": prompt_len}


def bench_kernel_scaling(on_tpu: bool) -> dict:
    """Per-layer decode attention at short vs long cached context with the
    SAME max_pages: if cost scales with max context (dense gather) the two
    times match; kernel times should scale with actual context."""
    from ray_tpu.ops.paged_attention import paged_decode_attention

    if on_tpu:
        B, H, KVH, D = 8, 16, 8, 128
        max_pages = 128                   # max ctx 2048
    else:
        B, H, KVH, D = 2, 4, 2, 64       # interpret mode is slow: tiny
        max_pages = 4
    page_size = 16
    num_pages = B * max_pages + 1
    rng = np.random.default_rng(0)
    k_pages = jnp.asarray(
        rng.normal(size=(num_pages, page_size, KVH, D)), jnp.bfloat16)
    v_pages = jnp.asarray(
        rng.normal(size=(num_pages, page_size, KVH, D)), jnp.bfloat16)
    tables = jnp.asarray(
        np.arange(B * max_pages).reshape(B, max_pages), jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.bfloat16)

    fn = jax.jit(lambda q, k, v, t, s: paged_decode_attention(
        q, k, v, t, s, interpret=not on_tpu))

    def timed(seq_len):
        lens = jnp.full((B,), seq_len, jnp.int32)
        out = fn(q, k_pages, v_pages, tables, lens)
        np.asarray(out)                       # sync
        iters = 20 if on_tpu else 2
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k_pages, v_pages, tables, lens)
        np.asarray(out)
        return (time.perf_counter() - t0) / iters * 1e3

    short = timed(page_size * max(max_pages // 16, 1))
    long = timed(page_size * max_pages)
    return {"short_ctx_ms": round(short, 3), "long_ctx_ms": round(long, 3),
            "long_over_short": round(long / max(short, 1e-9), 2)}


def bench_speculative(on_tpu: bool) -> dict:
    """Greedy decode throughput, speculative vs plain. SELF-draft
    (the target's own weights) pins acceptance near 1.0, isolating the
    structural effect: 2 dispatches per round for ~k tokens vs 1 per
    token. That wins exactly where per-dispatch latency dominates
    (TPU behind the tunnel — see BENCH_CORE per-call overhead); on
    CPU, where compute dominates and the draft doubles it, the row
    goes BELOW 1x by design — both regimes are the honest signal."""
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        target = _tpu_bench_model()
        batch, gen = 4, 96
    else:
        target = llama.config("debug")
        batch, gen = 2, 32
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, target.vocab_size, 32).tolist()
               for _ in range(batch)]

    tparams = llama.init_params(target, jax.random.PRNGKey(5))

    def run(spec):
        # params passed EXPLICITLY to both engines: self-draft is true
        # by construction, not by seed coupling with the engine's init
        eng = InferenceEngine(EngineConfig(
            model=target, max_batch_size=batch, num_pages=256,
            seed=5, enable_prefix_caching=False, speculative=spec),
            params=tparams)
        # full-length warmup: later rounds cross ctx-bucket
        # boundaries and would otherwise compile inside the timed run
        eng.generate([list(p) for p in prompts],
                     SamplingParams(max_tokens=gen))
        t0 = time.perf_counter()
        reqs = eng.generate([list(p) for p in prompts],
                            SamplingParams(max_tokens=gen))
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens) for r in reqs)
        return round(toks / dt, 1), eng.stats()

    plain_tps, _ = run(None)
    spec_k = int(os.environ.get("RAY_TPU_BENCH_SPEC_K", "4"))
    spec_tps, st = run({"draft_model": target,
                        "draft_params": tparams,
                        "num_speculative_tokens": spec_k})
    return {"plain_tokens_per_sec": plain_tps,
            "spec_tokens_per_sec": spec_tps,
            "spec_speedup": round(spec_tps / max(plain_tps, 1e-9), 2),
            "acceptance_rate": st.get("spec_acceptance_rate"),
            "tokens_per_round": st.get("spec_tokens_per_round")}


def bench_multi_step(on_tpu: bool) -> dict:
    """Greedy decode throughput at decode_steps_per_call = 1 vs K:
    K decode iterations per dispatch amortize the per-call overhead
    that dominates decode on the tunnel (145 ms/call vs ~3 ms compute
    floor measured round 4); on CPU, where dispatch is ~free, the row
    hovers near 1x by design."""
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              SamplingParams)
    from ray_tpu.models import llama

    if on_tpu:
        target = _tpu_bench_model()
        batch, gen, ksteps = 8, 96, int(os.environ.get(
            "RAY_TPU_BENCH_DECODE_K", "8"))
    else:
        target = llama.config("debug")
        batch, gen, ksteps = 2, 32, 4
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, target.vocab_size, 32).tolist()
               for _ in range(batch)]

    def run(k):
        eng = InferenceEngine(EngineConfig(
            model=target, max_batch_size=batch, num_pages=256, seed=5,
            enable_prefix_caching=False, decode_steps_per_call=k))
        eng.generate([list(p) for p in prompts],
                     SamplingParams(max_tokens=gen))     # warm/compile
        t0 = time.perf_counter()
        reqs = eng.generate([list(p) for p in prompts],
                            SamplingParams(max_tokens=gen))
        dt = time.perf_counter() - t0
        return round(sum(len(r.output_tokens) for r in reqs) / dt, 1)

    single = run(1)
    multi = run(ksteps)
    return {"k": ksteps, "single_tokens_per_sec": single,
            "multi_tokens_per_sec": multi,
            "multi_speedup": round(multi / max(single, 1e-9), 2)}


def bench_fleet(on_tpu: bool) -> dict:
    """ISSUE 6 fleet A/B: 2 in-process engine replicas behind the
    FleetManager (prefix-affine router + bounded admission) vs the
    same replicas under round-robin, plus a 1-replica baseline —
    bursty traffic where G tenant groups share 64-char prompt
    prefixes. Affinity keeps each group's prefix pages hot on ONE
    replica (misses ~= G, the first request per group); round-robin
    sprays the group across the fleet so every replica pays the cold
    prefill (misses ~= G * replicas). The overload phase floods a
    max_concurrent=2/max_queue=4 front door and checks the admission
    contract: surplus sheds as 429 and the p99 queue wait of everyone
    else stays bounded by the SLO instead of growing with the burst.
    Throughput of 2 replicas vs 1 is honest-signal only on TPU (two
    chips); on CPU both replicas share one host so the row hovers
    near 1x by design."""
    import asyncio
    import uuid

    from ray_tpu.llm._internal.server import LLMServerImpl
    from ray_tpu.serve.llm import (AdmissionConfig, AdmissionRejected,
                                   AutoscaleConfig, FleetManager,
                                   LocalReplicaClient, RouterConfig)
    from ray_tpu.models import llama

    if on_tpu:
        cfg = _tpu_bench_model()
        groups, rounds, gen = 8, 8, 32
        pages, batch, chunk = 512, 8, 128
    else:
        cfg = llama.config("debug")
        groups, rounds, gen = 4, 6, 4
        pages, batch, chunk = 128, 4, 32
    # 64-char shared prefixes (multiple of the byte tokenizer's
    # page granularity) — one per tenant group
    prefixes = [(f"tenant {g} shared context block " + "x" * 64)[:64]
                for g in range(groups)]

    def make_servers(n):
        tag = f"bench{uuid.uuid4().hex[:8]}"
        return {f"r{i}": LLMServerImpl({
            "model_id": "bench", "model_source": cfg,
            "engine_kwargs": dict(
                max_batch_size=batch, page_size=8, num_pages=pages,
                seed=7, max_prefill_tokens=chunk,
                metrics_model_id=tag, metrics_replica_id=f"r{i}"),
        }) for i in range(n)}

    def fleet_over(servers, policy, **adm):
        admission = AdmissionConfig(**adm) if adm else AdmissionConfig(
            max_concurrent=64, max_queue=128, queue_wait_slo_s=60.0)
        return FleetManager(
            [LocalReplicaClient(rid, srv)
             for rid, srv in servers.items()],
            router=RouterConfig(policy=policy, prefix_depth=64,
                                spill_waiting=batch * 4),
            admission=admission,
            autoscale=AutoscaleConfig(min_replicas=len(servers),
                                      max_replicas=len(servers)))

    def run_traffic(policy, n_replicas):
        """Bursty rounds: every group fires one request per round,
        all groups concurrently. Fresh engines per run so prefix-cache
        state never leaks across the A/B arms."""
        servers = make_servers(n_replicas)
        fleet = fleet_over(servers, policy)

        async def main():
            t0 = time.perf_counter()
            toks = 0
            for r in range(rounds):
                # rotate the group order per round: with it, a
                # round-robin fleet genuinely sprays each group across
                # replicas (in dispatch order it would be accidentally
                # sticky whenever groups % replicas == 0)
                order = prefixes[r % groups:] + prefixes[:r % groups]
                outs = await asyncio.gather(*(
                    fleet.dispatch("completions", {
                        "prompt": p + f" q{r}", "max_tokens": gen})
                    for p in order))
                toks += sum(o["usage"]["completion_tokens"]
                            for o in outs)
            dt = time.perf_counter() - t0
            for srv in servers.values():
                if srv._pump is not None:
                    srv._pump.cancel()
            return toks, dt

        toks, dt = asyncio.run(main())
        hit = sum(s.engine.allocator.cache_hit_tokens
                  for s in servers.values())
        query = sum(s.engine.allocator.cache_query_tokens
                    for s in servers.values())
        return {
            "tokens_per_sec": round(toks / dt, 1),
            "prefix_hit_rate": round(hit / max(query, 1), 4),
            "router": fleet.router.stats(),
        }

    affinity = run_traffic("affinity", 2)
    rr = run_traffic("round_robin", 2)
    single = run_traffic("affinity", 1)
    # the headline contract: affinity re-lands each group on its warm
    # replica, so the fleet-wide prefix-cache hit rate beats spraying
    assert affinity["prefix_hit_rate"] > rr["prefix_hit_rate"], (
        affinity, rr)

    # overload phase: flood a tiny front door; the contract is 429s
    # for the surplus + SLO-bounded queue wait for everyone else
    servers = make_servers(2)
    slo_s = 8.0
    fleet = fleet_over(servers, "affinity", max_concurrent=2,
                       max_queue=4, queue_wait_slo_s=slo_s)

    async def overload():
        results = await asyncio.gather(
            *(fleet.dispatch("completions", {
                "prompt": f"overload probe {i}", "max_tokens": 2})
              for i in range(24)),
            return_exceptions=True)
        for srv in servers.values():
            if srv._pump is not None:
                srv._pump.cancel()
        return results

    results = asyncio.run(overload())
    ok = sum(1 for r in results if isinstance(r, dict))
    shed = sum(1 for r in results if isinstance(r, AdmissionRejected))
    other = [r for r in results
             if not isinstance(r, (dict, AdmissionRejected))]
    assert not other, other
    adm = fleet.admission.stats()
    assert shed > 0 and ok > 0, (ok, shed)
    assert adm["queue_wait_p99_s"] <= slo_s + 0.5, adm

    return {
        "affinity_2rep": affinity,
        "round_robin_2rep": rr,
        "single_replica": single,
        "fleet_speedup": round(
            affinity["tokens_per_sec"]
            / max(single["tokens_per_sec"], 1e-9), 2),
        "affinity_hit_advantage": round(
            affinity["prefix_hit_rate"] - rr["prefix_hit_rate"], 4),
        "overload": {"completed": ok, "shed_429": shed,
                     "queue_wait_p99_s": adm["queue_wait_p99_s"],
                     "queue_wait_slo_s": slo_s},
    }


def bench_fleet_tracing(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 7 gate, two halves. Correctness: fleet serving with
    distributed tracing + the SLO watchdog on actually produces the
    observability goods — every request's ingress spans land in the
    trace buffer, the replica's lifecycle timeline carries the SAME
    trace id, and the watchdog consumed the replicas' totals.
    Overhead: the identical workload with enable_tracing=False and
    the watchdog disabled is the baseline — trace minting is a few
    dict ops per request at ingress and the watchdog runs on the
    control loop, not the request path, so the instrumented run must
    not be slower beyond timer noise (the dispatch-guard suite
    separately proves zero transfers / compiles). In --smoke mode
    both halves assert."""
    import asyncio
    import uuid

    from ray_tpu.llm._internal.server import LLMServerImpl
    from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                                   FleetManager, LocalReplicaClient,
                                   RouterConfig, WatchdogConfig,
                                   merge_fleet_traces)
    from ray_tpu.models import llama

    if on_tpu and not smoke:
        cfg = _tpu_bench_model()
        n_req, rounds, gen, pages, batch = 8, 6, 32, 512, 8
    else:
        cfg = llama.config("debug")
        n_req, rounds, gen, pages, batch = 4, 4, 12, 128, 4

    def run(enable_tracing):
        tag = f"trace{uuid.uuid4().hex[:8]}"
        servers = {"r0": LLMServerImpl({
            "model_id": "bench", "model_source": cfg,
            "engine_kwargs": dict(
                max_batch_size=batch, page_size=8, num_pages=pages,
                seed=7, metrics_model_id=tag,
                metrics_replica_id="r0"),
        })}
        fleet = FleetManager(
            [LocalReplicaClient(rid, srv)
             for rid, srv in servers.items()],
            router=RouterConfig(prefix_depth=64),
            admission=AdmissionConfig(max_concurrent=64,
                                      max_queue=128,
                                      queue_wait_slo_s=60.0),
            autoscale=AutoscaleConfig(min_replicas=1, max_replicas=1),
            watchdog=WatchdogConfig(enabled=enable_tracing),
            enable_tracing=enable_tracing)

        async def drive():
            toks = 0
            for r in range(rounds):
                outs = await asyncio.gather(*(
                    fleet.dispatch("completions", {
                        "prompt": f"trace bench {i} round {r}",
                        "max_tokens": gen})
                    for i in range(n_req)))
                toks += sum(o["usage"]["completion_tokens"]
                            for o in outs)
            for srv in servers.values():
                if srv._pump is not None:
                    srv._pump.cancel()
            return toks

        asyncio.run(drive())                 # warmup: compiles
        t0 = time.perf_counter()
        toks = asyncio.run(drive())
        dt = time.perf_counter() - t0
        if enable_tracing:
            # watchdog exercise rides the CONTROL loop in prod
            # (refresh cadence), not the request path — one tick
            # OUTSIDE the timed window proves the wiring without
            # biasing the overhead A/B against its own gate
            asyncio.run(fleet.autoscale_tick(now=0.0))
        return ({"tokens_per_sec": round(toks / dt, 1)},
                fleet, servers)

    on_row, fleet_on, servers_on = run(True)
    off_row, fleet_off, _ = run(False)

    # correctness half: the traced fleet produced the goods
    doc = merge_fleet_traces(
        {"r0": servers_on["r0"].engine.chrome_trace()},
        fleet_on.trace)
    evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    ingress_tids = {e["args"]["trace_id"] for e in evs
                    if e["name"] == "fleet_request"}
    replica_tids = {e["args"]["trace_id"] for e in evs
                    if e["name"] == "decode"
                    and "trace_id" in e["args"]}
    res = {
        "tracing_on": on_row, "tracing_off": off_row,
        "overhead_ratio": round(
            on_row["tokens_per_sec"]
            / max(off_row["tokens_per_sec"], 1e-9), 3),
        "ingress_spans": fleet_on.trace.stats()["total"],
        "traced_requests": len(ingress_tids),
        "trace_ids_joined": len(replica_tids & ingress_tids),
        "watchdog_observed": bool(fleet_on.watchdog.last),
        "untraced_buffer": fleet_off.trace.stats()["total"],
    }
    if smoke:
        assert res["ingress_spans"] > 0, res
        assert res["traced_requests"] == 2 * rounds * n_req, res
        assert res["trace_ids_joined"] > 0, (
            "no replica lifecycle joined an ingress trace id")
        assert res["watchdog_observed"], res
        assert res["untraced_buffer"] == 0, res
        # tripwire with slack for CI timer noise: ingress-side dict
        # ops must never make serving materially slower
        assert res["overhead_ratio"] >= 0.8, res
    return res


def bench_chaos(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 9 chaos gate: sever one replica mid-bursty-bench and
    prove the failure plane's contract — every client stream still
    completes, every transcript is token-exact vs a single-replica
    oracle (the failover continuation resumes the exact sequence),
    the dead replica leaves the ring, and p99 e2e stays bounded (the
    failover costs one re-route + one cached re-prefill, not a
    restart). Greedy decode is batching- and fleet-independent, so
    the oracle check covers the failover boundary exactly."""
    import asyncio
    import uuid

    from ray_tpu.llm._internal.server import LLMServerImpl
    from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                                   ChaosReplicaClient, ChaosSchedule,
                                   FleetManager, HealthConfig,
                                   LocalReplicaClient, RouterConfig)
    from ray_tpu.serve.llm.fleet import UNHEALTHY
    from ray_tpu.models import llama

    if on_tpu and not smoke:
        cfg = _tpu_bench_model()
        n_req, rounds, gen, pages, batch = 8, 4, 24, 512, 8
    else:
        cfg = llama.config("debug")
        n_req, rounds, gen, pages, batch = 6, 3, 8, 128, 4
    tag = f"chaos{uuid.uuid4().hex[:8]}"
    servers = {f"r{i}": LLMServerImpl({
        "model_id": "bench", "model_source": cfg,
        "engine_kwargs": dict(
            max_batch_size=batch, page_size=8, num_pages=pages,
            seed=7, metrics_model_id=tag, metrics_replica_id=f"r{i}"),
    }) for i in range(2)}
    schedules = {rid: ChaosSchedule(seed=13) for rid in servers}
    victim = "r0"
    # the victim's SECOND stream dies after 2 chunks — mid-burst,
    # with sibling streams live on both replicas
    schedules[victim].sever_stream(
        after_chunks=2, method="completions_stream_tokens", at_call=1)
    fleet = FleetManager(
        [ChaosReplicaClient(LocalReplicaClient(rid, srv),
                            schedules[rid])
         for rid, srv in servers.items()],
        router=RouterConfig(prefix_depth=64, spill_waiting=batch * 4),
        admission=AdmissionConfig(max_concurrent=64, max_queue=128,
                                  queue_wait_slo_s=60.0),
        autoscale=AutoscaleConfig(min_replicas=2, max_replicas=2),
        health=HealthConfig(open_cooldown_s=300.0),
        model_id="bench")

    def parse(chunks):
        toks, reasons = [], []
        for c in chunks:
            payload = c[len("data: "):].strip()
            if payload == "[DONE]":
                continue
            d = json.loads(payload)
            ch = d["choices"][0]
            toks += ch.get("token_ids") or []
            if ch["finish_reason"] is not None:
                reasons.append(ch["finish_reason"])
        return toks, reasons

    results = {}
    e2es = []

    async def one(prompt):
        t0 = time.perf_counter()
        chunks = []
        async for c in fleet.dispatch_stream(
                "completions_stream",
                {"prompt": prompt, "max_tokens": gen}):
            chunks.append(c)
        e2es.append(time.perf_counter() - t0)
        results[prompt] = parse(chunks)

    async def drive():
        for r in range(rounds):
            await asyncio.gather(*(
                one(f"chaos bench tenant {i} round {r}")
                for i in range(n_req)))
        for srv in servers.values():
            if srv._pump is not None:
                srv._pump.cancel()

    asyncio.run(drive())

    # oracle: fresh single replica, same weights seed
    oracle = LLMServerImpl({
        "model_id": "bench", "model_source": cfg,
        "engine_kwargs": dict(
            max_batch_size=batch, page_size=8, num_pages=pages,
            seed=7, metrics_model_id=f"or{uuid.uuid4().hex[:8]}")})

    async def oracle_toks(prompt):
        out = []
        async for c in oracle.completions_stream_tokens(
                {"prompt": prompt, "max_tokens": gen}):
            out.append(c)
        return [t for c in out for t in c["toks"]]

    async def oracle_drive():
        want = {}
        for p in results:
            want[p] = await oracle_toks(p)
        if oracle._pump is not None:
            oracle._pump.cancel()
        return want

    want = asyncio.run(oracle_drive())
    finished = sum(1 for toks, reasons in results.values()
                   if len(reasons) == 1)
    exact = sum(1 for p in results if results[p][0] == want[p])
    fired = [f for s in schedules.values() for f in s.fired]
    e2es.sort()
    p99 = e2es[min(len(e2es) - 1, int(len(e2es) * 0.99))]
    res = {
        "requests": len(results),
        "completed": finished,
        "token_exact": exact,
        "severs_fired": len(fired),
        "failovers": sum(
            v for _, v in fleet.metrics["failovers"]._samples()),
        "victim_evicted": fleet.replicas[victim].status == UNHEALTHY,
        "p99_e2e_s": round(p99, 3),
        "median_e2e_s": round(e2es[len(e2es) // 2], 3),
    }
    # the contract asserts in every mode: chaos must never corrupt
    assert res["severs_fired"] >= 1, res
    assert res["completed"] == res["requests"], res
    assert res["token_exact"] == res["requests"], res
    assert res["victim_evicted"], res
    assert res["p99_e2e_s"] <= 8.0, res
    return res


def bench_preemption(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 10 gate: a 2x page-oversubscribed bursty workload (device
    pages capped at half the fleet's worst-case KV demand, optimistic
    watermark admission) must COMPLETE every stream token-exact vs an
    un-oversubscribed oracle — "out of pages" is a latency tier
    (spill to the host tier, park, restore token-exact), not a hard
    reject — with at least one spill AND one restore actually
    observed, zero capacity rejects, zero error finishes, and the
    preempted tail's p99 e2e bounded (the cost of parking is waiting
    for pages, not corruption or restarts). BENCH_CORE.md: "KV memory
    hierarchy anatomy"."""
    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama

    if on_tpu and not smoke:
        cfg = _tpu_bench_model()
        batch, plen, gen, burst, every = 8, 96, 64, 6, 12
    else:
        cfg = llama.config("debug")
        batch, plen, gen, burst, every = 4, 12, 44, 6, 10
    n_req = 18
    page = 8
    # worst case per request in pages, resident-batch demand, and the
    # 2x-oversubscribed device pool (usable = num_pages - 1)
    per = -(-(plen + gen) // page)
    demand = batch * per
    pages_over = demand // 2 + 1
    rng = np.random.default_rng(23)
    prompts = [rng.integers(1, cfg.vocab_size, plen).tolist()
               for _ in range(n_req)]

    def run(num_pages, offload):
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=batch, page_size=page,
            num_pages=num_pages, seed=5, prefill_buckets=(16, 32, 64,
                                                          128),
            max_prefill_tokens=32, enable_kv_offload=offload,
            kv_watermark_tokens=8 if offload else None))
        reqs = [Request(f"p{i}", list(p),
                        SamplingParams(max_tokens=gen))
                for i, p in enumerate(prompts)]
        done_at = {}
        t0 = time.perf_counter()
        submit_at = {}
        pending = list(reqs)
        steps = 0
        while eng.has_work() or pending:
            if pending and steps % every == 0:
                for r in pending[:burst]:
                    submit_at[r.request_id] = time.perf_counter()
                    eng.add_request(r)      # 0 capacity rejects
                pending = pending[burst:]
            for r in eng.step():
                if r.finished and r.request_id not in done_at:
                    done_at[r.request_id] = time.perf_counter()
            steps += 1
            assert steps < 100_000
        e2es = sorted(done_at[r.request_id]
                      - submit_at[r.request_id] for r in reqs)
        return eng, reqs, {
            "wall_s": round(time.perf_counter() - t0, 3),
            "p50_e2e_s": round(e2es[len(e2es) // 2], 3),
            "p99_e2e_s": round(
                e2es[min(len(e2es) - 1, int(len(e2es) * 0.99))], 3),
        }

    _, oracle_reqs, oracle_times = run(demand * 2, offload=False)
    eng, reqs, times = run(pages_over, offload=True)
    tier = eng.host_tier
    exact = sum(o.output_tokens == r.output_tokens
                for o, r in zip(oracle_reqs, reqs))
    res = {
        "requests": n_req,
        "completed": sum(r.finished for r in reqs),
        "token_exact": exact,
        "error_finishes": sum(r.finish_reason == "error"
                              for r in reqs),
        "device_pages": pages_over - 1,
        "worst_case_demand_pages": demand,
        "spills": tier.spills_total,
        "restores": tier.restores_total,
        "preemptions": dict(eng.preempt_counts),
        "host_pages_peak": tier.spilled_pages_total,
        "oversubscribed": times,
        "oracle": oracle_times,
    }
    # the contract asserts in every mode: oversubscription must never
    # reject, corrupt, or wedge
    assert res["completed"] == n_req, res
    assert res["token_exact"] == n_req, res
    assert res["error_finishes"] == 0, res
    assert res["spills"] >= 1 and res["restores"] >= 1, res
    assert times["p99_e2e_s"] <= max(8.0,
                                     8 * oracle_times["p99_e2e_s"]), res
    return res


def _disagg_servers(n, cfg, pages, batch, chunk):
    import uuid

    from ray_tpu.llm._internal.server import LLMServerImpl

    tag = f"kvt{uuid.uuid4().hex[:8]}"
    return {f"r{i}": LLMServerImpl({
        "model_id": "bench", "model_source": cfg,
        "engine_kwargs": dict(
            max_batch_size=batch, page_size=8, num_pages=pages,
            seed=7, max_prefill_tokens=chunk,
            enable_kv_offload=True,
            metrics_model_id=tag, metrics_replica_id=f"r{i}"),
    }) for i in range(n)}


def bench_disagg(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 12 disaggregation A/B: a mixed long-prompt/short-decode
    burst on 2 MIXED replicas vs 1 PREFILL + 1 DECODE over the fleet
    KV transport. In the mixed arm every long prompt's chunked
    prefill shares a tick budget with running decodes; in the
    disaggregated arm the prefill replica absorbs the long prompts
    and ships the parked sessions, so the decode replica's ticks
    stay pure decode — the client-side decode inter-token gap (ITL
    p99 over the short streams) is the headline. CPU numbers are
    honest-signal only for the CONTRACT (token-exact handoffs, ships
    observed); both arms share one host here, so the latency split
    shows its real gap on TPU. `--smoke` asserts the disaggregated
    path is token-exact vs a single-engine oracle."""
    import asyncio

    from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                                   FleetManager, LocalReplicaClient,
                                   RouterConfig, TransportConfig)
    from ray_tpu.models import llama

    if on_tpu:
        cfg = _tpu_bench_model()
        long_chars, gen_long, gen_short = 2048, 16, 64
        n_long, n_short, rounds = 4, 8, 3
        pages, batch, chunk = 512, 8, 128
    else:
        cfg = llama.config("debug")
        long_chars, gen_long, gen_short = 160, 6, 24
        n_long, n_short, rounds = 2, 4, 2
        pages, batch, chunk = 160, 4, 32

    def fleet_over(servers, roles):
        return FleetManager(
            [LocalReplicaClient(rid, srv)
             for rid, srv in servers.items()],
            router=RouterConfig(prefix_depth=64,
                                spill_waiting=batch * 4),
            admission=AdmissionConfig(max_concurrent=64,
                                      max_queue=128,
                                      queue_wait_slo_s=60.0),
            autoscale=AutoscaleConfig(min_replicas=len(servers),
                                      max_replicas=len(servers)),
            roles=roles,
            transport=TransportConfig(disagg_prompt_chars=128,
                                      enable_prefix_store=False))

    def run(roles):
        servers = _disagg_servers(2, cfg, pages, batch, chunk)
        fleet = fleet_over(servers, roles)
        gaps = []

        async def one(prompt, gen, collect):
            last = None
            async for c in fleet.dispatch_stream(
                    "completions_stream",
                    {"prompt": prompt, "max_tokens": gen}):
                if "[DONE]" in c:
                    continue
                now = time.perf_counter()
                if collect and last is not None:
                    gaps.append(now - last)
                last = now

        async def drive():
            t0 = time.perf_counter()
            for r in range(rounds):
                jobs = [one(f"long context r{r} i{i} "
                            + "x" * long_chars, gen_long, False)
                        for i in range(n_long)]
                jobs += [one(f"short q r{r} i{i}", gen_short, True)
                         for i in range(n_short)]
                await asyncio.gather(*jobs)
            dt = time.perf_counter() - t0
            for srv in servers.values():
                if srv._pump is not None:
                    srv._pump.cancel()
            return dt

        dt = asyncio.run(drive())
        gaps.sort()
        evs = [e["event"] for e in fleet.recorder.events()]
        hit = sum(s.engine.allocator.cache_hit_tokens
                  for s in servers.values())
        query = sum(s.engine.allocator.cache_query_tokens
                    for s in servers.values())
        toks = rounds * (n_long * gen_long + n_short * gen_short)
        return {
            "tokens_per_sec": round(toks / dt, 1),
            "decode_itl_p99_ms": round(
                gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))]
                * 1e3, 3) if gaps else None,
            "decode_itl_p50_ms": round(
                gaps[len(gaps) // 2] * 1e3, 3) if gaps else None,
            "fleet_prefix_hit_rate": round(hit / max(query, 1), 4),
            "sessions_shipped": evs.count("disagg_handoff"),
            "disagg_fallbacks": evs.count("disagg_fallback"),
        }

    # correctness half (always, and the whole of --smoke): one long
    # prompt through the disaggregated fleet vs a single-engine
    # oracle, token-exact
    servers = _disagg_servers(2, cfg, pages, batch, chunk)
    fleet = fleet_over(servers, ["prefill", "decode"])
    body = {"prompt": "exactness probe " + "y" * long_chars,
            "max_tokens": gen_short}

    async def probe():
        toks = []
        async for c in fleet.dispatch_stream("completions_stream",
                                             dict(body)):
            if not c.startswith("data: "):
                continue
            d = c[len("data: "):].strip()
            if d == "[DONE]":
                continue
            toks += json.loads(d)["choices"][0].get("token_ids") \
                or []
        for srv in servers.values():
            if srv._pump is not None:
                srv._pump.cancel()
        return toks

    got = asyncio.run(probe())
    oracle = _disagg_servers(1, cfg, pages, batch, chunk)["r0"]

    async def oracle_probe():
        out = []
        async for c in oracle.completions_stream_tokens(dict(body)):
            out.append(c)
        if oracle._pump is not None:
            oracle._pump.cancel()
        return [t for c in out for t in c["toks"]]

    want = asyncio.run(oracle_probe())
    shipped = [e["event"] for e in fleet.recorder.events()] \
        .count("disagg_handoff")
    assert got == want, "disaggregated path diverged from oracle"
    assert shipped == 1, shipped
    exact = {"token_exact": True, "tokens": len(got),
             "sessions_shipped": shipped}
    if smoke:
        return {"exactness": exact}
    disagg = run(["prefill", "decode"])
    mixed = run(None)
    assert disagg["sessions_shipped"] >= rounds * n_long \
        - disagg["disagg_fallbacks"], disagg
    return {"exactness": exact, "disaggregated_1p1d": disagg,
            "mixed_2rep": mixed}


def bench_prefix_store(on_tpu: bool) -> dict:
    """ISSUE 12c A/B — the acceptance gate: on a shared-system-prompt
    workload, the fleet prefix-store hit rate must be STRICTLY above
    the PR 6 per-replica baseline (same fleet, same deterministic
    routing, store off). One warm request publishes the prefix; every
    other replica's FIRST request of that prefix then imports the
    pages instead of cold-prefilling — the per-replica cache
    multiplied by fleet size."""
    import asyncio
    import uuid

    from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                                   FleetManager, LocalReplicaClient,
                                   RouterConfig, TransportConfig)
    from ray_tpu.models import llama

    if on_tpu:
        cfg = _tpu_bench_model()
        gen, per_round, rounds = 16, 8, 2
        pages, batch, chunk = 512, 8, 128
    else:
        cfg = llama.config("debug")
        gen, per_round, rounds = 4, 4, 2
        pages, batch, chunk = 160, 4, 32
    # 64 chars = the router's prefix depth = 8 full byte-tokenizer
    # pages: exactly the chain the store ships
    sys_prompt = (f"system prompt {uuid.uuid4().hex[:8]} "
                  + "s" * 64)[:64]

    def run(store):
        servers = _disagg_servers(2, cfg, pages, batch, chunk)
        fleet = FleetManager(
            [LocalReplicaClient(rid, srv)
             for rid, srv in servers.items()],
            # round-robin pins IDENTICAL routing in both arms, so the
            # only difference is the store seeding the cold replica
            router=RouterConfig(policy="round_robin",
                                prefix_depth=64),
            admission=AdmissionConfig(max_concurrent=64,
                                      max_queue=128,
                                      queue_wait_slo_s=60.0),
            autoscale=AutoscaleConfig(min_replicas=2,
                                      max_replicas=2),
            transport=(TransportConfig(enable_disagg=False,
                                       prefix_min_chars=64)
                       if store else None))

        async def drive():
            # the system prompt is prefilled ONCE, sequentially —
            # with the store on, this publishes it fleet-wide
            await fleet.dispatch("completions", {
                "prompt": sys_prompt + " warmup", "max_tokens": gen})
            for r in range(rounds):
                await asyncio.gather(*(
                    fleet.dispatch("completions", {
                        "prompt": sys_prompt + f" user {r}-{i}",
                        "max_tokens": gen})
                    for i in range(per_round)))
            for srv in servers.values():
                if srv._pump is not None:
                    srv._pump.cancel()

        asyncio.run(drive())
        hit = sum(s.engine.allocator.cache_hit_tokens
                  for s in servers.values())
        query = sum(s.engine.allocator.cache_query_tokens
                    for s in servers.values())
        return {
            "fleet_prefix_hit_rate": round(hit / max(query, 1), 4),
            "store": (fleet.prefix_store.stats()
                      if fleet.prefix_store is not None else None),
        }

    baseline = run(False)
    store = run(True)
    # THE gate: the shared tier strictly beats per-replica caches
    assert store["fleet_prefix_hit_rate"] \
        > baseline["fleet_prefix_hit_rate"], (store, baseline)
    assert store["store"]["publishes"] >= 1
    assert store["store"]["hits"] >= 1
    return {
        "per_replica_baseline": baseline,
        "fleet_store": store,
        "hit_rate_advantage": round(
            store["fleet_prefix_hit_rate"]
            - baseline["fleet_prefix_hit_rate"], 4),
    }


def bench_sim(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 14 gate, three parts.

    Determinism: the same seed + trace replayed twice through the
    fleet simulator produce BYTE-identical run summaries (the
    what-if tool is useless if two runs of one scenario disagree).

    Calibration band: a small real-engine workload (measured wall)
    vs the simulator's prediction from the committed CPU calibration
    — the ratio must sit inside CALIBRATION_BAND, so a stale
    calibration file fails loudly instead of quietly skewing every
    capacity curve.

    Batch-lane A/B: identical interactive traffic with the lane off
    vs on (plus a bulk backlog): recovered batch tokens > 0, every
    job completes, and the interactive p99 TTFT is unchanged (the
    lane soaks troughs, it must never be the thing that queues a
    user). In --smoke mode all three assert."""
    import time as _t

    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              Request,
                                              SamplingParams)
    from ray_tpu.serve.llm.sim import (FleetSimulator, SimFleetConfig,
                                       SimSession, TraceConfig,
                                       batch_backlog,
                                       default_cpu_calibration,
                                       generate)
    from ray_tpu.serve.llm import AdmissionConfig
    from tools.simcal import check_against

    calib = default_cpu_calibration()
    tc = TraceConfig(kind="diurnal", sessions=20_000,
                     duration_s=7200.0, seed=23, prefix_groups=64,
                     prompt_tokens_mean=24, prompt_tokens_max=96,
                     out_tokens_mean=12, out_tokens_max=48)

    def cfg():
        return SimFleetConfig(
            replicas=4, min_replicas=2, slots_per_replica=8,
            pages_per_replica=2048, calibration=calib, seed=23,
            admission=AdmissionConfig(max_concurrent=96,
                                      max_queue=256,
                                      queue_wait_slo_s=5.0))

    # -- determinism --------------------------------------------------
    t0 = time.perf_counter()
    a = FleetSimulator(generate(tc), cfg())
    a.run()
    sim_wall = time.perf_counter() - t0
    b = FleetSimulator(generate(tc), cfg())
    b.run()
    identical = a.summary_json() == b.summary_json()

    # -- calibration band: real mini-workload vs sim prediction -------
    n, plen, out = 8, 24, 12
    eng = InferenceEngine(EngineConfig(
        model="debug", max_batch_size=8, page_size=16, num_pages=96,
        max_prefill_tokens=128, enable_blackbox=False, seed=0))
    warm = Request("warm", list(range(2, 2 + plen)),
                   SamplingParams(max_tokens=4))
    eng.add_request(warm)
    while not warm.finished:
        eng.step()
    reqs = [Request(f"w{i}", list(range(2 + i, 2 + i + plen)),
                    SamplingParams(max_tokens=out))
            for i in range(n)]
    t0 = _t.monotonic()
    for r in reqs:
        eng.add_request(r)
    while not all(r.finished for r in reqs):
        eng.step()
    real_wall = _t.monotonic() - t0
    sessions = [SimSession(0.0, "t", i, plen, out, sid=i)
                for i in range(n)]
    mini = FleetSimulator(
        iter(sessions),
        SimFleetConfig(replicas=1, min_replicas=1,
                       slots_per_replica=8, pages_per_replica=96,
                       calibration=calib, seed=23,
                       control_period_s=0.05))
    verdict = check_against(calib, mini.run(), real_wall)

    # -- batch-lane soak A/B ------------------------------------------
    def soak(jobs):
        sim = FleetSimulator(generate(tc), cfg(), batch_jobs=jobs)
        return sim.run()

    off = soak([])
    on = soak(batch_backlog(500, out_tokens=24))
    p99_off = off["latency"]["ttft"]["p99_ms"]
    p99_on = on["latency"]["ttft"]["p99_ms"]
    mean_off = off["latency"]["ttft"]["mean_ms"]
    mean_on = on["latency"]["ttft"]["mean_ms"]
    res = {
        "deterministic": identical,
        "sim_sessions_per_host_s": round(
            tc.sessions / max(sim_wall, 1e-9), 1),
        "calibration": verdict,
        "batch_ab": {
            "recovered_tokens": on["batch"]["tokens"],
            "batch_completed": on["batch"]["completed"],
            "interactive_p99_ttft_ms_off": p99_off,
            "interactive_p99_ttft_ms_on": p99_on,
            "interactive_mean_ttft_ms_off": mean_off,
            "interactive_mean_ttft_ms_on": mean_on,
        },
    }
    if smoke:
        assert identical, "sim summaries diverged for one seed"
        assert verdict["within_band"], verdict
        assert on["batch"]["completed"] == 500
        assert on["batch"]["tokens"] > 0
        # zero interactive TAIL regression (the acceptance
        # criterion): p99 slack is EXACTLY one 1.15x log-histogram
        # bin — quantization, not a regression window. The MEAN may
        # shift by a couple of tick-times: interactive sessions
        # co-resident with soaked batch work run in a larger batch
        # (slightly longer ticks) — that is the lane working as
        # designed, so it is bounded absolutely, not relatively
        assert p99_on <= p99_off * 1.16 + 1.0, res
        assert mean_on <= mean_off + 4 * calib.tick_point(8, "p50"), \
            res
    return res


def bench_sanitizer(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 18 gate: the runtime thread sanitizer's two contracts.

    Disarmed — the production default — make_lock hands the engine a
    plain threading.Lock (verified structurally: no wrapper, so
    serving pays zero sanitizer overhead). Armed, a bursty
    multithreaded run (the pump stepping while scrape threads hammer
    stats / lane_counts / fleet_counters / abort, prompts landing
    mid-decode) completes with ZERO recorded violations: the lock
    discipline racelint proves statically also holds at runtime under
    real contention."""
    import threading

    from ray_tpu.llm._internal.engine import (EngineConfig,
                                              InferenceEngine,
                                              Request, SamplingParams)
    from ray_tpu.models import llama
    from ray_tpu.util import thread_sanitizer as ts

    cfg = llama.config("debug")
    n_req, max_tokens = (6, 24) if smoke else (12, 64)

    def build():
        eng = InferenceEngine(EngineConfig(
            model=cfg, max_batch_size=4, page_size=8, num_pages=160,
            prefill_buckets=(16, 32, 64), seed=7, unified_step=True))
        rng = np.random.default_rng(3)
        reqs = [Request(f"b{i}", rng.integers(2, 250, 12).tolist(),
                        SamplingParams(max_tokens=max_tokens))
                for i in range(n_req)]
        return eng, reqs

    # disarmed: the default engine must hold a bare stdlib lock
    eng, _ = build()
    plain = type(eng._step_lock) is type(threading.Lock())
    assert plain, "disarmed engine must hold a plain threading.Lock"

    t0 = time.perf_counter()
    with ts.sanitized():
        eng, reqs = build()     # built armed: traced step lock
        traced = isinstance(eng._step_lock, ts._TracedLock)
        assert traced, "armed engine must hold a traced lock"
        stop = threading.Event()
        errs: list = []

        def scrape():
            try:
                while not stop.is_set():
                    eng.stats()
                    eng.lane_counts()
                    eng.fleet_counters()
                    eng.has_work()
                    eng.abort("no-such-id")
            except BaseException as exc:   # pragma: no cover
                errs.append(exc)

        threads = [threading.Thread(target=scrape, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        for r in reqs[:2]:
            eng.add_request(r)
        admitted, ticks = 2, 0
        try:
            while not all(r.finished for r in reqs) and ticks < 5000:
                eng.step()
                ticks += 1
                if ticks % 5 == 0 and admitted < n_req:
                    # the burst: a new prompt lands mid-decode
                    eng.add_request(reqs[admitted])
                    admitted += 1
        finally:
            stop.set()
            for t in threads:
                t.join(30)
        viol = ts.violations()
    wall = time.perf_counter() - t0
    assert not errs, errs
    assert all(r.finished for r in reqs), "bursty workload must drain"
    assert viol == [], viol
    return {"disarmed_plain_lock": plain, "armed_traced_lock": traced,
            "ticks": ticks, "requests": n_req,
            "violations": len(viol), "wall_s": round(wall, 3)}


def bench_traffic_capture(on_tpu: bool, smoke: bool = False) -> dict:
    """ISSUE 20 gate: the traffic recorder's three production
    contracts, end to end.

    (1) Overhead: the same bursty workload runs with the capture
    disarmed (ring-only recording — the always-on default) and armed
    (segment encoding on every record); armed throughput must hold
    >= 0.7x disarmed (the encoding itself costs ~1%; the floor
    absorbs engine timing noise at smoke sizes). (2) Privacy: the capture bytes never contain
    the prompt tripwire. (3) Replay: the sealed capture replays
    through the fleet simulator deterministically (same bytes ->
    byte-identical summary) and the capture-diff lands inside the
    calibration band (p99 latency ratio, prefix-hit-rate and
    route-mix drift)."""
    import asyncio
    import uuid

    from ray_tpu.llm._internal.server import LLMServerImpl
    from ray_tpu.serve.llm import (AdmissionConfig, AutoscaleConfig,
                                   FleetManager, LocalReplicaClient,
                                   RouterConfig, WatchdogConfig)
    from ray_tpu.serve.llm.trafficlog import decode_capture
    from ray_tpu.models import llama
    from tools import tracereplay

    secret = "zanzibar beacon"                  # privacy tripwire
    if on_tpu:
        cfg = _tpu_bench_model()
        streams, rounds, gen = 24, 3, 32
        batch, pages = 8, 512
    else:
        cfg = llama.config("debug")
        streams, rounds, gen = 12, 2, 16
        batch, pages = 4, 128
    # 4 prefix chains: requests within a chain share an IDENTICAL
    # prompt (identical fingerprint -> one router group); one chain
    # carries the tripwire so the scrubbing proof covers real text.
    # Tiny prompts on purpose: the burst oversubscribes the engine
    # slots, so latency is queue/decode-dominated on both the real
    # and the simulated side rather than riding the prefill pricing.
    chains = [f"c{g}" if g else f"c0 {secret}" for g in range(4)]

    tag = f"cap{uuid.uuid4().hex[:8]}"
    servers = {f"r{i}": LLMServerImpl({
        "model_id": "capbench", "model_source": cfg,
        "engine_kwargs": dict(
            max_batch_size=batch, page_size=8, num_pages=pages,
            seed=7, metrics_model_id=tag,
            metrics_replica_id=f"r{i}")}) for i in range(2)}
    fleet = FleetManager(
        [LocalReplicaClient(rid, srv)
         for rid, srv in servers.items()],
        router=RouterConfig(prefix_depth=64),
        admission=AdmissionConfig(max_concurrent=2 * streams,
                                  max_queue=4 * streams),
        autoscale=AutoscaleConfig(min_replicas=2, max_replicas=2),
        watchdog=WatchdogConfig(enabled=False),
        model_id=tag)

    async def burst(seed0):
        t0 = time.perf_counter()
        toks = 0
        for r in range(rounds):
            outs = await asyncio.gather(*(
                fleet.dispatch("completions", {
                    "prompt": chains[i % len(chains)],
                    "max_tokens": gen, "temperature": 0.5,
                    "seed": seed0 + i, "user": f"tenant-{i % 2}"})
                for i in range(streams)))
            toks += sum(o["usage"]["completion_tokens"]
                        for o in outs)
        return toks, time.perf_counter() - t0

    async def run_all():
        # two warmup bursts: the first compiles the fresh-prefill
        # shapes AND populates the prefix cache; the second hits that
        # cache and compiles the cached-prefix decode shapes the
        # steady state actually runs
        await burst(10_000)
        await burst(15_000)
        toks_off, dt_off = await burst(20_000)  # disarmed arm
        fleet.traffic.start_capture("bench")
        toks_on, dt_on = await burst(30_000)    # armed arm
        sealed = fleet.traffic.stop_capture()
        text = fleet.traffic.export()
        await fleet.stop()
        return toks_off, dt_off, toks_on, dt_on, sealed, text

    toks_off, dt_off, toks_on, dt_on, sealed, text = \
        asyncio.run(run_all())
    for srv in servers.values():
        if srv._pump is not None:
            srv._pump.cancel()

    tps_off = toks_off / dt_off
    tps_on = toks_on / dt_on
    overhead_ratio = tps_on / max(tps_off, 1e-9)

    # privacy: no prompt text in the capture bytes
    assert secret not in text
    for word in secret.split():
        assert word not in text

    # deterministic replay + the banded capture-diff
    cap = decode_capture(text)
    assert sealed["records"] == rounds * streams
    s1 = tracereplay.replay_sim(cap, replicas=2,
                                slots_per_replica=batch)
    s2 = tracereplay.replay_sim(cap, replicas=2,
                                slots_per_replica=batch)
    assert json.dumps(s1, sort_keys=True) == json.dumps(
        s2, sort_keys=True), "replay must be deterministic"
    diff = tracereplay.capture_diff(cap, s1)
    if smoke:
        assert overhead_ratio >= 0.7, (tps_off, tps_on)
        assert diff["pass"], diff["failures"]
    return {
        "records": sealed["records"],
        "capture_bytes": sealed["bytes"],
        "tokens_per_sec_disarmed": round(tps_off, 1),
        "tokens_per_sec_armed": round(tps_on, 1),
        "overhead_ratio": round(overhead_ratio, 3),
        "replay_pass": diff["pass"],
        "replay_failures": diff["failures"],
        "recorded_p99_e2e_ms":
            diff["recorded"]["latency"]["e2e"]["p99_ms"],
        "replayed_p99_e2e_ms":
            diff["replayed"]["latency"]["e2e"]["p99_ms"],
        "prefix_hit_rate": {
            "recorded": diff["recorded"]["prefix_hit_rate"],
            "replayed": diff["replayed"]["prefix_hit_rate"]},
    }


def main() -> None:
    import sys
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if "--smoke" in sys.argv:
        # CI mode: tiny model, CPU, fast — one JSON line whose
        # dispatches_per_step and kernel_tick rows fail loudly on
        # scheduler / kernel regressions
        mixed = bench_mixed(on_tpu, smoke=True)
        kernel = bench_kernel_tick(on_tpu)
        async_ab = bench_async_ab(on_tpu, smoke=True)
        telemetry = bench_telemetry(on_tpu, smoke=True)
        fleet_tracing = bench_fleet_tracing(on_tpu, smoke=True)
        chaos = bench_chaos(on_tpu, smoke=True)
        preemption = bench_preemption(on_tpu, smoke=True)
        perf = bench_perf_accounting(on_tpu, smoke=True)
        # ISSUE 13: per-request receipts conserve exactly + on/off
        # overhead A/B within noise
        attribution = bench_attribution(on_tpu, smoke=True)
        # ISSUE 16: quantized-vs-f32 serving A/B — KV bytes >= 1.9x
        # narrower, logprob deltas and token agreement in band
        quant_ab = bench_quant_ab(on_tpu, smoke=True)
        # ISSUE 12: disaggregated prefill/decode must be token-exact
        # vs a single-engine oracle (the ship really happened)
        disagg = bench_disagg(on_tpu, smoke=True)
        # ISSUE 14: simulator determinism + calibration band +
        # batch-lane soak A/B (recovered tokens, zero interactive
        # p99 regression)
        sim = bench_sim(on_tpu, smoke=True)
        # ISSUE 18: disarmed engine holds a plain stdlib lock (zero
        # sanitizer overhead); armed bursty multithreaded run records
        # zero lock-discipline violations
        sanitizer = bench_sanitizer(on_tpu, smoke=True)
        # ISSUE 20: armed-capture overhead >= 0.7x disarmed, no
        # prompt text in capture bytes, and the sealed capture
        # replays deterministically inside the calibration band
        traffic = bench_traffic_capture(on_tpu, smoke=True)
        print(json.dumps({
            "metric": "llm_mixed_smoke",
            "value": mixed["unified"]["tokens_per_sec"],
            "unit": "tokens_per_sec",
            "detail": {**mixed, "kernel_tick": kernel,
                       "async_readback_ab": async_ab,
                       "telemetry": telemetry,
                       "fleet_tracing": fleet_tracing,
                       "chaos": chaos,
                       "preemption": preemption,
                       "perf": perf,
                       "attribution": attribution,
                       "quant_ab": quant_ab,
                       "disagg": disagg,
                       "sim": sim,
                       "sanitizer": sanitizer,
                       "traffic_capture": traffic},
        }))
        return
    if "--fleet" in sys.argv:
        # ISSUE 6 A/B: prefix-affine routing vs round-robin over a
        # 2-replica in-process fleet + admission overload contract;
        # ISSUE 12 rides along: the disaggregation A/B and the fleet
        # prefix-store-vs-per-replica-baseline gate
        fleet = bench_fleet(on_tpu)
        disagg = bench_disagg(on_tpu)
        store = bench_prefix_store(on_tpu)
        print(json.dumps({
            "metric": "llm_fleet" if on_tpu else "llm_fleet_cpu",
            "value": fleet["affinity_2rep"]["tokens_per_sec"],
            "unit": "tokens_per_sec",
            "detail": {**fleet, "disagg": disagg,
                       "prefix_store": store},
        }))
        return
    if "--long-ctx" in sys.argv:
        # ISSUE 2 A/B: gather vs Pallas ragged kernel at long context
        long_ctx = bench_long_ctx(on_tpu)
        print(json.dumps({
            "metric": "llm_long_ctx" if on_tpu
                      else "llm_long_ctx_cpu_interpret",
            "value": long_ctx["kernel"]["tokens_per_sec"],
            "unit": "tokens_per_sec",
            "detail": long_ctx,
        }))
        return
    eng = bench_engine(on_tpu)
    mixed = bench_mixed(on_tpu)
    async_ab = bench_async_ab(on_tpu)
    telemetry = bench_telemetry(on_tpu)
    perf = bench_perf_accounting(on_tpu)
    attribution = bench_attribution(on_tpu)
    scaling = bench_kernel_scaling(on_tpu)
    prefix = bench_prefix_cache(on_tpu)
    spec = bench_speculative(on_tpu)
    multi = bench_multi_step(on_tpu)
    print(json.dumps({
        "metric": "llm_decode_tokens_per_sec" if on_tpu
                  else "llm_decode_tokens_per_sec_cpu_fallback",
        "value": eng["decode_tokens_per_sec"],
        "unit": "tokens_per_sec",
        "detail": {"device": getattr(dev, "device_kind", str(dev)),
                   **eng, "mixed_prefill_decode": mixed,
                   "async_readback_ab": async_ab,
                   "telemetry": telemetry,
                   "perf": perf,
                   "attribution": attribution,
                   "paged_kernel_scaling": scaling,
                   "prefix_cache": prefix, "speculative": spec,
                   "multi_step_decode": multi},
    }))


if __name__ == "__main__":
    main()
